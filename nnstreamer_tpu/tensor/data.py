"""Typed scalar/aggregate ops used by transform and if elements.

TPU-native equivalent of ``tensor_data_s`` ops (reference:
gst/nnstreamer/tensor_data.c:78-454).  The reference keeps a tagged-union
scalar with per-dtype C switch statements; here numpy handles dtype dispatch
and we only keep the semantic API: typecast, average, std, per-channel
variants.
"""

from __future__ import annotations


import numpy as np

from .types import TensorType


def typecast(value, dtype: TensorType):
    """Scalar typecast with C-style saturation-free semantics (reference:
    gst_tensor_data_typecast, tensor_data.c:213-300)."""
    return np.asarray(value).astype(dtype.np_dtype)


def average(arr: np.ndarray) -> np.float64:
    """Mean over all elements as float64 (reference:
    gst_tensor_data_raw_average, tensor_data.c:330-360)."""
    return np.float64(np.mean(np.asarray(arr, dtype=np.float64)))


def average_per_channel(arr: np.ndarray, *, channel_axis: int = -1) -> np.ndarray:
    """Per-channel mean (reference: gst_tensor_data_raw_average_per_channel,
    tensor_data.c:368-400; the reference's "channel" is dim[0], the innermost
    axis, which is numpy axis -1)."""
    a = np.asarray(arr, dtype=np.float64)
    axes = tuple(i for i in range(a.ndim) if i != (channel_axis % a.ndim))
    return np.mean(a, axis=axes)


def std(arr: np.ndarray) -> np.float64:
    """Population standard deviation (reference:
    gst_tensor_data_raw_std, tensor_data.c:408-440)."""
    return np.float64(np.std(np.asarray(arr, dtype=np.float64)))


def std_per_channel(arr: np.ndarray, *, channel_axis: int = -1) -> np.ndarray:
    a = np.asarray(arr, dtype=np.float64)
    axes = tuple(i for i in range(a.ndim) if i != (channel_axis % a.ndim))
    return np.std(a, axis=axes)
