"""Per-buffer tensor meta header for flexible / sparse streams.

TPU-native equivalent of ``GstTensorMetaInfo`` (reference:
gst/nnstreamer/include/tensor_typedef.h:263-296; header serialize/parse at
nnstreamer_plugin_api_util_impl.c:1237-1435).  A flexible stream's every
payload is prefixed with this binary header so each buffer can carry its own
shape/dtype; a sparse payload additionally records ``nnz`` and is laid out as
``values[nnz] ++ indices[nnz]``.

Wire format (little-endian, 128 bytes fixed):

    uint32 magic        (0x544e4e53, "SNNT")
    uint32 version      (1)
    uint32 type         (TensorType index, table below)
    uint32 format       (0 static, 1 flexible, 2 sparse)
    uint32 media_type
    uint32 rank
    uint32 dims[8]
    uint32 sparse_nnz
    uint8  reserved[...]  (pad to 128)

The reference's header is 128 bytes as well (``META_HEADER_SIZE`` via
gst_tensor_meta_info_get_header_size).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Tuple

import numpy as np

from .types import (
    Dimension,
    TENSOR_RANK_LIMIT,
    TensorFormat,
    TensorType,
    dim_element_count,
)
from .info import TensorInfo

META_MAGIC = 0x544E4E53  # "SNNT"
META_VERSION = 1
META_HEADER_SIZE = 128

# Stable wire ids for dtypes (do NOT reorder; append only).
_TYPE_IDS = [
    TensorType.INT32, TensorType.UINT32, TensorType.INT16, TensorType.UINT16,
    TensorType.INT8, TensorType.UINT8, TensorType.FLOAT64, TensorType.FLOAT32,
    TensorType.INT64, TensorType.UINT64, TensorType.FLOAT16,
    TensorType.BFLOAT16,
]
_TYPE_TO_ID = {t: i for i, t in enumerate(_TYPE_IDS)}

_FORMAT_IDS = [TensorFormat.STATIC, TensorFormat.FLEXIBLE, TensorFormat.SPARSE]
_FORMAT_TO_ID = {f: i for i, f in enumerate(_FORMAT_IDS)}

_HEADER_STRUCT = struct.Struct("<6I8II")  # magic..rank, dims[8], nnz


@dataclasses.dataclass
class TensorMetaInfo:
    """Parsed per-buffer tensor meta (reference: GstTensorMetaInfo)."""

    dtype: TensorType
    dims: Dimension
    format: TensorFormat = TensorFormat.FLEXIBLE
    media_type: int = 0
    sparse_nnz: int = 0

    def to_bytes(self) -> bytes:
        """Serialize to the fixed 128-byte header (reference:
        gst_tensor_meta_info_update_header)."""
        rank = len(self.dims)
        if rank > TENSOR_RANK_LIMIT:
            raise ValueError(f"rank {rank} exceeds {TENSOR_RANK_LIMIT}")
        dims = list(self.dims) + [0] * (TENSOR_RANK_LIMIT - rank)
        payload = _HEADER_STRUCT.pack(
            META_MAGIC, META_VERSION, _TYPE_TO_ID[self.dtype],
            _FORMAT_TO_ID[self.format], self.media_type, rank,
            *dims, self.sparse_nnz)
        return payload + b"\x00" * (META_HEADER_SIZE - len(payload))

    @classmethod
    def from_bytes(cls, data: bytes) -> "TensorMetaInfo":
        """Parse the fixed header (reference: gst_tensor_meta_info_parse_header,
        nnstreamer_plugin_api_util_impl.c:1397-1435)."""
        if len(data) < META_HEADER_SIZE:
            raise ValueError(f"short meta header: {len(data)} bytes")
        fields = _HEADER_STRUCT.unpack_from(data, 0)
        magic, version, type_id, fmt_id, media_type, rank = fields[:6]
        dims = fields[6:14]
        nnz = fields[14]
        if magic != META_MAGIC:
            raise ValueError(f"bad meta magic 0x{magic:08x}")
        if version != META_VERSION:
            raise ValueError(f"unsupported meta version {version}")
        return cls(dtype=_TYPE_IDS[type_id], dims=tuple(dims[:rank]),
                   format=_FORMAT_IDS[fmt_id], media_type=media_type,
                   sparse_nnz=nnz)

    @classmethod
    def from_info(cls, info: TensorInfo,
                  format: TensorFormat = TensorFormat.FLEXIBLE) -> "TensorMetaInfo":
        return cls(dtype=info.dtype, dims=info.dims, format=format)

    def to_info(self) -> TensorInfo:
        """Reference: gst_tensor_meta_info_convert."""
        return TensorInfo(dtype=self.dtype, dims=self.dims)

    @property
    def data_size(self) -> int:
        """Payload byte size described by this meta (reference:
        gst_tensor_meta_info_get_data_size).  For sparse format this is the
        values+indices layout size."""
        esz = self.dtype.element_size
        if self.format is TensorFormat.SPARSE:
            return self.sparse_nnz * (esz + 4 * TENSOR_RANK_LIMIT)
        return dim_element_count(self.dims) * esz


def wrap_flex(arr: np.ndarray, meta: Optional[TensorMetaInfo] = None) -> bytes:
    """Prefix a raw tensor payload with its flexible meta header."""
    from ..pipeline.tracing import record_copy

    if meta is None:
        meta = TensorMetaInfo.from_info(TensorInfo.from_np(arr))
    record_copy(META_HEADER_SIZE + arr.nbytes)
    return meta.to_bytes() + np.ascontiguousarray(arr).tobytes()


def unwrap_flex(data: bytes) -> Tuple[TensorMetaInfo, np.ndarray]:
    """Split a flexible payload into (meta, ndarray view)."""
    meta = TensorMetaInfo.from_bytes(data)
    raw = np.frombuffer(data, dtype=np.uint8, offset=META_HEADER_SIZE,
                        count=meta.data_size)
    from .types import dim_to_np_shape

    arr = raw.view(meta.dtype.np_dtype).reshape(dim_to_np_shape(meta.dims))
    return meta, arr
