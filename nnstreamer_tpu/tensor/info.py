"""Tensor info/config records and their parse/print/compare utilities.

TPU-native re-design of ``GstTensorInfo`` / ``GstTensorsInfo`` /
``GstTensorsConfig`` (reference: gst/nnstreamer/include/tensor_typedef.h:222-260
and the util impls in nnstreamer_plugin_api_util_impl.c).  These are plain
immutable-ish Python dataclasses; "validate" maps to :meth:`is_valid` and the
copy/free pairs collapse into dataclass copies.
"""

from __future__ import annotations

import dataclasses
from fractions import Fraction
from typing import List, Optional, Tuple

import numpy as np

from .types import (
    Dimension,
    TENSOR_RANK_LIMIT,
    TENSOR_SIZE_LIMIT,
    TENSOR_SIZE_EXTRA_LIMIT,
    TensorFormat,
    TensorType,
    dim_element_count,
    dim_is_static,
    dim_parse,
    dim_to_np_shape,
    dim_to_string,
    dims_equal,
)


@dataclasses.dataclass
class TensorInfo:
    """Metadata of a single tensor: name, dtype, dimension.

    Reference: ``GstTensorInfo`` tensor_typedef.h:222-231.
    """

    dtype: Optional[TensorType] = None
    dims: Dimension = ()
    name: Optional[str] = None

    # -- validation / size ---------------------------------------------------
    def is_valid(self) -> bool:
        """Reference: gst_tensor_info_validate
        (nnstreamer_plugin_api_util_impl.c:133-147)."""
        return self.dtype is not None and dim_is_static(self.dims)

    @property
    def element_count(self) -> int:
        return dim_element_count(self.dims)

    @property
    def size(self) -> int:
        """Byte size of one frame of this tensor.

        Reference: gst_tensor_info_get_size
        (nnstreamer_plugin_api_util_impl.c:156-170).
        """
        if not self.is_valid():
            raise ValueError(f"invalid tensor info: {self}")
        return self.element_count * self.dtype.element_size

    @property
    def np_shape(self) -> Tuple[int, ...]:
        return dim_to_np_shape(self.dims)

    @property
    def np_dtype(self) -> np.dtype:
        if self.dtype is None:
            raise ValueError("tensor info has no dtype")
        return self.dtype.np_dtype

    # -- compare -------------------------------------------------------------
    def is_equal(self, other: "TensorInfo") -> bool:
        """Dtype+dims equality, rank-lenient; names are not compared.

        Reference: gst_tensor_info_is_equal
        (nnstreamer_plugin_api_util_impl.c:182-205).
        """
        if self.dtype is None or other.dtype is None:
            return False
        return self.dtype is other.dtype and dims_equal(self.dims, other.dims)

    # -- parse / print -------------------------------------------------------
    @classmethod
    def from_np(cls, arr: np.ndarray, name: Optional[str] = None) -> "TensorInfo":
        from .types import np_shape_to_dim

        return cls(dtype=TensorType.from_np(arr.dtype),
                   dims=np_shape_to_dim(arr.shape), name=name)

    def to_string(self) -> str:
        return f"{self.dtype},{dim_to_string(self.dims)}"

    def __str__(self) -> str:
        return (f"TensorInfo(name={self.name!r} type={self.dtype} "
                f"dims={dim_to_string(self.dims)})")

    def copy(self) -> "TensorInfo":
        return dataclasses.replace(self)


@dataclasses.dataclass
class TensorsInfo:
    """Ordered collection of :class:`TensorInfo` (≤16 base + extra).

    Reference: ``GstTensorsInfo`` tensor_typedef.h:233-243; extra-tensor
    handling nnstreamer_plugin_api_util_impl.c:57-111.
    """

    infos: List[TensorInfo] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        limit = TENSOR_SIZE_LIMIT + TENSOR_SIZE_EXTRA_LIMIT
        if len(self.infos) > limit:
            raise ValueError(f"too many tensors: {len(self.infos)} > {limit}")

    @property
    def num_tensors(self) -> int:
        return len(self.infos)

    def __len__(self) -> int:
        return len(self.infos)

    def __getitem__(self, i: int) -> TensorInfo:
        return self.infos[i]

    def __iter__(self):
        return iter(self.infos)

    def append(self, info: TensorInfo) -> None:
        if len(self.infos) >= TENSOR_SIZE_LIMIT + TENSOR_SIZE_EXTRA_LIMIT:
            raise ValueError("tensor count limit reached")
        self.infos.append(info)

    def is_valid(self) -> bool:
        """Reference: gst_tensors_info_validate
        (nnstreamer_plugin_api_util_impl.c:590-612)."""
        return self.num_tensors > 0 and all(i.is_valid() for i in self.infos)

    def is_equal(self, other: "TensorsInfo") -> bool:
        """Reference: gst_tensors_info_is_equal
        (nnstreamer_plugin_api_util_impl.c:620-644)."""
        if self.num_tensors != other.num_tensors:
            return False
        return all(a.is_equal(b) for a, b in zip(self.infos, other.infos))

    # -- parse / print (reference: gst_tensors_info_parse_*_string and
    #    gst_tensors_info_get_*_string,
    #    nnstreamer_plugin_api_util_impl.c:652-899) ---------------------------
    @classmethod
    def from_strings(cls, dims: str, types: str,
                     names: Optional[str] = None) -> "TensorsInfo":
        """Build from ``"3:224:224,10"`` style dim and ``"uint8,float32"``
        style type strings (comma- or dot-separated per reference caps)."""
        dim_list = _split_multi(dims)
        type_list = _split_multi(types)
        if len(dim_list) != len(type_list):
            raise ValueError(
                f"dims/types count mismatch: {len(dim_list)} vs {len(type_list)}")
        name_list: List[Optional[str]] = [None] * len(dim_list)
        if names:
            parsed = [n.strip() or None for n in _split_multi(names)]
            if len(parsed) != len(dim_list):
                raise ValueError("names count mismatch")
            name_list = parsed
        infos = [
            TensorInfo(dtype=TensorType.from_string(t), dims=dim_parse(d),
                       name=n)
            for d, t, n in zip(dim_list, type_list, name_list)
        ]
        return cls(infos=infos)

    def dims_string(self, sep: str = ",") -> str:
        """``sep="."`` is the in-caps separator (reference caps use ``.``
        because ``,`` delimits caps fields)."""
        return sep.join(dim_to_string(i.dims) for i in self.infos)

    def types_string(self, sep: str = ",") -> str:
        return sep.join(str(i.dtype) for i in self.infos)

    def names_string(self, sep: str = ",") -> str:
        return sep.join(i.name or "" for i in self.infos)

    def total_size(self) -> int:
        return sum(i.size for i in self.infos)

    def copy(self) -> "TensorsInfo":
        return TensorsInfo(infos=[i.copy() for i in self.infos])

    def __str__(self) -> str:
        return f"TensorsInfo[{', '.join(str(i) for i in self.infos)}]"


DEFAULT_FRAMERATE = Fraction(0, 1)


@dataclasses.dataclass
class TensorsConfig:
    """Stream-level configuration: tensors info + framerate + format.

    Reference: ``GstTensorsConfig`` tensor_typedef.h:245-260 (rate_n/rate_d
    become a :class:`fractions.Fraction`; ``info`` keeps its role).
    """

    info: TensorsInfo = dataclasses.field(default_factory=TensorsInfo)
    rate: Optional[Fraction] = None  # None = unspecified; 0/1 = "static" src
    format: TensorFormat = TensorFormat.STATIC

    def is_valid(self) -> bool:
        """Reference: gst_tensors_config_validate
        (nnstreamer_plugin_api_util_impl.c:932-955): flexible/sparse streams
        don't require static per-tensor info; static streams do.  A known
        framerate is required for a fully-negotiated stream."""
        if self.rate is None:
            return False
        if self.format is not TensorFormat.STATIC:
            return True
        return self.info.is_valid()

    def is_equal(self, other: "TensorsConfig") -> bool:
        """Reference: gst_tensors_config_is_equal
        (nnstreamer_plugin_api_util_impl.c:963-984)."""
        if self.format is not other.format:
            return False
        if (self.rate or DEFAULT_FRAMERATE) != (other.rate or DEFAULT_FRAMERATE):
            return False
        if self.format is TensorFormat.STATIC:
            return self.info.is_equal(other.info)
        return True

    def copy(self) -> "TensorsConfig":
        return TensorsConfig(info=self.info.copy(), rate=self.rate,
                             format=self.format)

    def __str__(self) -> str:
        rate = "?" if self.rate is None else f"{self.rate.numerator}/{self.rate.denominator}"
        return f"TensorsConfig(format={self.format} rate={rate} info={self.info})"


def _split_multi(s: str) -> List[str]:
    """Split a caps list string on ``,`` (reference also accepts ``.`` as the
    separator inside caps strings because ``,`` delimits caps fields;
    nnstreamer_plugin_api_util_impl.c:672-676)."""
    s = s.strip()
    if not s:
        return []
    sep = "," if "," in s else "."
    return [p for p in s.split(sep)]
