"""Stream buffer: one timestamped frame of N tensors.

TPU-native equivalent of a ``GstBuffer`` holding N ``GstMemory`` chunks of
tensor data (reference hot-path handling: tensor_filter.c:631-894;
gst_tensor_buffer_get_nth_memory nnstreamer_plugin_api_impl.c:1549).

Design differences, deliberately TPU-first:

- A tensor payload is an *array handle*, not raw bytes: either a numpy
  ndarray (host) or a ``jax.Array`` (device/HBM).  Elements pass handles
  zero-copy; nothing forces a device→host sync until a consumer calls
  :meth:`TensorBuffer.np` — this is what keeps the filter hot loop async
  (the reference's equivalent discipline is zero-copy mapping + at-most-one
  output alloc, tensor_filter.c:671-779).
- PTS/DTS/duration are integer nanoseconds like GStreamer clock-time.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import numpy as np

#: Sentinel for "no timestamp" (GStreamer GST_CLOCK_TIME_NONE analogue).
CLOCK_TIME_NONE: Optional[int] = None


def is_device_array(x: Any) -> bool:
    """True when ``x`` is a jax.Array (device-resident handle) or a
    :class:`BatchView` into one."""
    # Avoid importing jax at module import time for host-only tooling.
    cls = x.__class__
    return (cls.__module__.startswith("jax")
            or hasattr(x, "addressable_shards")
            or isinstance(x, BatchView))


class BatchView:
    """Zero-copy per-frame view into a batched device array.

    Net-new TPU-native concept (no reference counterpart; the closest
    discipline is the zero-copy GstMemory mapping of tensor_filter.c:
    631-894): a batched ``tensor_filter`` invoke produces ONE device array
    of shape ``(bucket, *frame_shape)`` per output.  Instead of syncing it
    to host and slicing into numpy rows, the filter can emit one BatchView
    per frame — the batch stays in HBM, and:

    - a DOWNSTREAM device consumer (another batched filter) recognizes
      contiguous views over the same underlying array and feeds the batch
      straight back into its own executable — the cascade's intermediate
      tensors never leave the device, and no per-frame device ops run;
    - a host consumer (decoder/sink/numpy code) triggers ``__array__``,
      which materializes the WHOLE underlying batch once (one d2h per
      batch, cached and shared by all sibling views) and returns its row.

    Views are immutable handles; ``shape``/``dtype``/``nbytes`` describe
    the single frame, not the batch.
    """

    __slots__ = ("batch", "index", "_cache")

    def __init__(self, batch: Any, index: int, cache: dict) -> None:
        self.batch = batch      # jax.Array, shape (bucket, *frame_shape)
        self.index = int(index)
        self._cache = cache     # shared per underlying array: {"host": np}

    @property
    def shape(self):
        return tuple(self.batch.shape[1:])

    @property
    def dtype(self):
        return self.batch.dtype

    @property
    def nbytes(self) -> int:
        n = int(np.dtype(self.batch.dtype).itemsize)
        for d in self.batch.shape[1:]:
            n *= int(d)
        return n

    def device_slice(self):
        """This frame as its own device array (dispatches one slice op —
        the slow path; batch-aware consumers use ``batch`` directly)."""
        return self.batch[self.index]

    def _host_batch(self) -> np.ndarray:
        host = self._cache.get("host")
        if host is None:
            host = self._cache["host"] = np.asarray(self.batch)
        return host

    def __array__(self, dtype=None, copy=None):
        row = self._host_batch()[self.index]
        if dtype is not None and row.dtype != np.dtype(dtype):
            return row.astype(dtype)
        # always hand out an independent row: the host batch is SHARED by
        # sibling views, and consumers may mutate what they np.asarray'd
        # (jax.Array.__array__ gives the same independence guarantee)
        return row.copy()

    def __repr__(self) -> str:
        return (f"BatchView(row {self.index} of "
                f"{tuple(self.batch.shape)} {self.batch.dtype})")


@dataclasses.dataclass
class TensorBuffer:
    """One frame of a tensor stream: N tensor payloads + timestamps.

    ``tensors`` entries are numpy arrays or jax Arrays.  ``metas`` carries an
    optional per-tensor :class:`~nnstreamer_tpu.tensor.meta.TensorMetaInfo`
    for flexible/sparse streams (None for static streams).
    """

    tensors: List[Any] = dataclasses.field(default_factory=list)
    pts: Optional[int] = CLOCK_TIME_NONE
    duration: Optional[int] = CLOCK_TIME_NONE
    metas: Optional[List[Any]] = None
    #: free-form per-buffer metadata (e.g. query client id — reference
    #: tensor_meta.c query_client_id_t).
    extra: dict = dataclasses.field(default_factory=dict)

    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def np(self, i: int = 0) -> np.ndarray:
        """Materialize tensor ``i`` on host (device sync happens HERE and
        only here)."""
        t = self.tensors[i]
        if isinstance(t, np.ndarray):
            return t
        return np.asarray(t)

    def nbytes(self) -> int:
        total = 0
        for t in self.tensors:
            total += t.nbytes if hasattr(t, "nbytes") else len(t)
        return total

    def with_tensors(self, tensors: Sequence[Any]) -> "TensorBuffer":
        """New buffer with same timestamps/extra but different payloads."""
        return TensorBuffer(tensors=list(tensors), pts=self.pts,
                            duration=self.duration, extra=dict(self.extra))

    def copy(self) -> "TensorBuffer":
        """Shallow copy: a new wrapper with independent ``extra``/``metas``
        containers but the SAME tensor payload handles — no tensor bytes are
        copied, and device arrays stay on device."""
        return TensorBuffer(tensors=list(self.tensors), pts=self.pts,
                            duration=self.duration,
                            metas=list(self.metas) if self.metas else None,
                            extra=dict(self.extra))

    def __repr__(self) -> str:
        shapes = ",".join(str(getattr(t, "shape", "?")) for t in self.tensors)
        return f"TensorBuffer(n={self.num_tensors} shapes=[{shapes}] pts={self.pts})"


SECOND = 1_000_000_000


def frames_to_ns(frame_index: int, rate_num: int, rate_den: int) -> int:
    """PTS of frame N at a given framerate, in ns."""
    if rate_num == 0:
        return 0
    return frame_index * SECOND * rate_den // rate_num
