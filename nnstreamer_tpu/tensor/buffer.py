"""Stream buffer: one timestamped frame of N tensors.

TPU-native equivalent of a ``GstBuffer`` holding N ``GstMemory`` chunks of
tensor data (reference hot-path handling: tensor_filter.c:631-894;
gst_tensor_buffer_get_nth_memory nnstreamer_plugin_api_impl.c:1549).

Design differences, deliberately TPU-first:

- A tensor payload is an *array handle*, not raw bytes: either a numpy
  ndarray (host) or a ``jax.Array`` (device/HBM).  Elements pass handles
  zero-copy; nothing forces a device→host sync until a consumer calls
  :meth:`TensorBuffer.np` — this is what keeps the filter hot loop async
  (the reference's equivalent discipline is zero-copy mapping + at-most-one
  output alloc, tensor_filter.c:671-779).
- PTS/DTS/duration are integer nanoseconds like GStreamer clock-time.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..analysis import sanitizer as _san

#: Sentinel for "no timestamp" (GStreamer GST_CLOCK_TIME_NONE analogue).
CLOCK_TIME_NONE: Optional[int] = None


# ---------------------------------------------------------------------------
# pool refcount baselines, calibrated at import.  The no-alias guarantee
# rides on sys.getrefcount: a slab is recycled only when nothing outside
# the pool machinery can reach it.  How many references the machinery
# itself holds at the check sites depends on the interpreter (CPython
# 3.10 keeps call arguments alive on the evaluation stack; 3.11+
# doesn't), so measure the exact call shapes instead of hardcoding.
# ---------------------------------------------------------------------------

def _probe_refcount(x) -> int:
    return sys.getrefcount(x)


def _calibrate_reclaim() -> int:
    # shape of _reclaim/__del__: caller local → callee param → getrefcount
    local = bytearray(1)
    return _probe_refcount(local)


def _calibrate_sweep() -> int:
    # shape of _sweep_pending_locked: list entry → loop var → getrefcount
    lst = [bytearray(1)]
    for slab in lst:
        return sys.getrefcount(slab)
    return 3


#: refcount a slab shows inside ``_reclaim`` when ONLY the caller holds
#: it — anything above means external views are alive
_RECLAIM_BASELINE = _calibrate_reclaim()
#: same for the pending-list sweep
_SWEEP_BASELINE = _calibrate_sweep()


class BufferLease:
    """One leased slab of a :class:`TensorBufferPool`.

    The lease is the ownership handle for a pooled payload: transports
    receive wire bytes into :meth:`memory` and decode zero-copy numpy
    views over it; the slab returns to the pool's free list when the
    last reference lets go (explicit :meth:`release`, or the lease
    being dropped — CPython refcounting makes the drop path prompt).

    Recycling is SAFE BY CONSTRUCTION, not by convention: a slab is
    only reused when nothing else can still see it.  At reclaim time
    the pool checks the slab's external reference count — any live
    numpy view / memoryview over the slab keeps a reference chain to
    it — and a slab with outstanding views is parked on a pending list
    instead of the free list (re-checked on later acquires), so a
    writer can never scribble over bytes an old view still aliases.
    """

    __slots__ = ("_pool", "_slab", "size", "_refs", "_lock")

    def __init__(self, pool: "TensorBufferPool", slab: bytearray,
                 size: int) -> None:
        self._pool = pool
        self._slab = slab
        self.size = size
        self._refs = 1
        self._lock = _san.make_lock("lease")

    @property
    def nbytes(self) -> int:
        return self.size

    def memory(self) -> memoryview:
        """Writable memoryview of exactly ``size`` bytes."""
        slab = self._slab
        if slab is None:
            raise RuntimeError("BufferLease used after release")
        if _san._ENABLED:
            # writable grant while decoded views are alive = the
            # aliasing bug the pool exists to prevent (sanitizer)
            _san.check_writable_grant(slab, "BufferLease.memory")
        return memoryview(slab)[:self.size]

    def view(self, dtype, shape, offset: int = 0) -> np.ndarray:
        """Zero-copy ndarray over the payload (marked read-only: pooled
        payloads are shared, same contract as tee fan-out)."""
        count = 1
        for d in shape:
            count *= int(d)
        arr = np.frombuffer(self.memory(), dtype=dtype, count=count,
                            offset=offset).reshape(shape)
        arr.flags.writeable = False
        return arr

    def retain(self) -> "BufferLease":
        with self._lock:
            if self._slab is None:
                raise RuntimeError("BufferLease retained after release")
            self._refs += 1
        return self

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            if self._refs > 0:
                return
            slab, self._slab = self._slab, None
        if slab is not None:
            self._pool._reclaim(slab)

    def __del__(self):
        # safety net: an unreleased lease dying returns its slab (the
        # common pipeline flow never calls release explicitly — the
        # buffer wrapper dropping at the sink is the release)
        slab = getattr(self, "_slab", None)
        if slab is not None:
            self._slab = None
            self._pool._reclaim(slab)


class TensorBufferPool:
    """Recycled payload slabs for the dataflow hot path.

    The role of GStreamer's GstBufferPool for this framework's wire /
    ring transports: ``acquire(n)`` hands out a :class:`BufferLease`
    over a ``bytearray`` slab, exact-size free lists make same-shaped
    streams hit the pool every frame, and ``stats`` exposes
    ``hits``/``misses`` so copy and allocation behavior is observable
    (surfaced per element as ``pool_hit`` by pipeline/tracing.py).
    """

    def __init__(self, max_per_bucket: int = 16,
                 max_free_bytes: int = 128 << 20) -> None:
        self.max_per_bucket = max_per_bucket
        #: cap on TOTAL retained free bytes across all size buckets —
        #: per-bucket caps alone would let a variable-size stream
        #: (flex tensors, renegotiating caps) grow one bucket per
        #: distinct payload size without bound.  At the cap, reclaim
        #: evicts the largest free bucket before retaining.
        self.max_free_bytes = max_free_bytes
        self._free: Dict[int, List[bytearray]] = {}
        self._free_bytes = 0
        self._pending: List[bytearray] = []   # slabs with live views
        self._lock = _san.make_lock("pool")
        # slabs whose reclaim found the lock held (see _reclaim); deque
        # append/popleft are atomic under the GIL, so __del__ can park
        # here without taking any lock
        import collections

        self._deferred: "collections.deque" = collections.deque()
        self.hits = 0
        self.misses = 0

    def acquire(self, nbytes: int) -> BufferLease:
        nbytes = int(nbytes)
        with self._lock:
            self._drain_deferred_locked()
            self._sweep_pending_locked()
            bucket = self._free.get(nbytes)
            if bucket:
                slab = bucket.pop()
                if not bucket:
                    # drop the emptied bucket: variable-size streams must
                    # not accrete one dict entry per distinct payload size
                    # (the byte-cap eviction scores empty buckets 0, so
                    # they would never be evicted)
                    del self._free[nbytes]
                self._free_bytes -= nbytes
                self.hits += 1
                hit = True
            else:
                slab = None
                self.misses += 1
                hit = False
        if slab is None:
            slab = bytearray(nbytes)
        elif _san._ENABLED:
            # a recycled slab must have NO live views (sanitizer cross-
            # checks the refcount reclaim invariant independently)
            _san.check_slab_reissue(slab)
        from ..pipeline import tracing

        tracing.record_pool(hit)
        return BufferLease(self, slab, nbytes)

    def _sweep_pending_locked(self) -> None:
        """Move parked slabs whose last external view died back to the
        free lists (refcount 2 = the pending list + getrefcount's
        argument: nothing else can reach the slab)."""
        if not self._pending:
            return
        still = []
        for slab in self._pending:
            if sys.getrefcount(slab) <= _SWEEP_BASELINE:
                self._retain_free_locked(slab)
            else:
                still.append(slab)
        self._pending = still

    def _retain_free_locked(self, slab: bytearray) -> None:
        """Add a quiescent slab to the free lists, respecting both the
        per-bucket cap and the pool-wide byte cap (evicting the largest
        other bucket once before giving up)."""
        n = len(slab)
        # look up WITHOUT creating: a cap-rejected retention of a new size
        # must not leave a permanently-empty bucket behind (empty buckets
        # score 0 in the eviction key below, so they'd never be evicted)
        bucket = self._free.get(n)
        if bucket is not None and len(bucket) >= self.max_per_bucket:
            return
        if self._free_bytes + n > self.max_free_bytes:
            victim = max(self._free, key=lambda s: s * len(self._free[s]),
                         default=None)
            if victim is None or victim == n:
                return
            self._free_bytes -= victim * len(self._free.pop(victim))
            if self._free_bytes + n > self.max_free_bytes:
                return
        if bucket is None:
            bucket = self._free.setdefault(n, [])
        bucket.append(slab)
        self._free_bytes += n

    def _reclaim(self, slab: bytearray) -> None:
        # non-blocking acquire: _reclaim is reachable from
        # BufferLease.__del__, and cyclic GC can fire that __del__ on the
        # very thread currently INSIDE a locked pool section (the lock is
        # not reentrant — a blocking acquire would self-deadlock).  When
        # the lock is unavailable, park the slab on the lock-free deferred
        # queue; the next locked section routes it through _pending.
        if not self._lock.acquire(blocking=False):
            self._deferred.append(slab)
            return
        try:
            # a live numpy view / memoryview over the slab holds a
            # reference chain to it; recycling now would let the next
            # writer alias it.  Park such slabs; they rejoin the free
            # list once the views die (checked on later acquires).
            # NOTE: body stays inline — _RECLAIM_BASELINE is calibrated
            # for exactly this caller-local → param → getrefcount shape.
            if sys.getrefcount(slab) > _RECLAIM_BASELINE:
                if len(self._pending) < 4 * self.max_per_bucket:
                    self._pending.append(slab)
                return
            self._retain_free_locked(slab)
        finally:
            self._lock.release()

    def _drain_deferred_locked(self) -> None:
        """Move lock-contended reclaims into the pending list: the sweep
        that follows applies its own calibrated view-aliasing check, so
        deferred slabs take the conservative park-then-sweep route
        instead of re-deriving a refcount baseline for this call shape."""
        while True:
            try:
                slab = self._deferred.popleft()
            except IndexError:
                return
            if len(self._pending) < 4 * self.max_per_bucket:
                self._pending.append(slab)

    @property
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "free": sum(len(b) for b in self._free.values()),
                    "free_bytes": self._free_bytes,
                    "pending": len(self._pending)}


_DEFAULT_POOL: Optional[TensorBufferPool] = None
_DEFAULT_POOL_LOCK = _san.make_lock("leaf")


def default_pool() -> TensorBufferPool:
    """Process-wide pool shared by the query/edge/shm transports."""
    global _DEFAULT_POOL
    if _DEFAULT_POOL is None:
        with _DEFAULT_POOL_LOCK:
            if _DEFAULT_POOL is None:
                _DEFAULT_POOL = TensorBufferPool()
                _register_pool_gauges(_DEFAULT_POOL)
    return _DEFAULT_POOL


def _register_pool_gauges(pool: TensorBufferPool) -> None:
    """Occupancy/hit-rate gauges for the shared pool — lazy callables,
    evaluated only when the metrics endpoint scrapes (obs/metrics.py)."""
    from ..obs.metrics import REGISTRY

    REGISTRY.gauge("nns_pool_free_bytes",
                   fn=lambda: pool._free_bytes, pool="default")
    REGISTRY.gauge("nns_pool_free_slabs",
                   fn=lambda: sum(len(b) for b in pool._free.values()),
                   pool="default")
    REGISTRY.gauge("nns_pool_pending_slabs",
                   fn=lambda: len(pool._pending), pool="default")
    REGISTRY.gauge(
        "nns_pool_hit_rate",
        fn=lambda: pool.hits / max(1, pool.hits + pool.misses),
        pool="default")


def is_device_array(x: Any) -> bool:
    """True when ``x`` is a jax.Array (device-resident handle) or a
    :class:`BatchView` into one."""
    # Avoid importing jax at module import time for host-only tooling.
    cls = x.__class__
    return (cls.__module__.startswith("jax")
            or hasattr(x, "addressable_shards")
            or isinstance(x, BatchView))


class BatchView:
    """Zero-copy per-frame view into a batched device array.

    Net-new TPU-native concept (no reference counterpart; the closest
    discipline is the zero-copy GstMemory mapping of tensor_filter.c:
    631-894): a batched ``tensor_filter`` invoke produces ONE device array
    of shape ``(bucket, *frame_shape)`` per output.  Instead of syncing it
    to host and slicing into numpy rows, the filter can emit one BatchView
    per frame — the batch stays in HBM, and:

    - a DOWNSTREAM device consumer (another batched filter) recognizes
      contiguous views over the same underlying array and feeds the batch
      straight back into its own executable — the cascade's intermediate
      tensors never leave the device, and no per-frame device ops run;
    - a host consumer (decoder/sink/numpy code) triggers ``__array__``,
      which materializes the WHOLE underlying batch once (one d2h per
      batch, cached and shared by all sibling views) and returns its row.

    Views are immutable handles; ``shape``/``dtype``/``nbytes`` describe
    the single frame, not the batch.
    """

    __slots__ = ("batch", "index", "_cache")

    def __init__(self, batch: Any, index: int, cache: dict) -> None:
        self.batch = batch      # jax.Array, shape (bucket, *frame_shape)
        self.index = int(index)
        self._cache = cache     # shared per underlying array: {"host": np}

    @property
    def shape(self):
        return tuple(self.batch.shape[1:])

    @property
    def dtype(self):
        return self.batch.dtype

    @property
    def nbytes(self) -> int:
        n = int(np.dtype(self.batch.dtype).itemsize)
        for d in self.batch.shape[1:]:
            n *= int(d)
        return n

    def device_slice(self):
        """This frame as its own device array (dispatches one slice op —
        the slow path; batch-aware consumers use ``batch`` directly)."""
        return self.batch[self.index]

    def _host_batch(self) -> np.ndarray:
        host = self._cache.get("host")
        if host is None:
            host = self._cache["host"] = np.asarray(self.batch)
        return host

    def __array__(self, dtype=None, copy=None):
        row = self._host_batch()[self.index]
        if dtype is not None and row.dtype != np.dtype(dtype):
            return row.astype(dtype)
        # always hand out an independent row: the host batch is SHARED by
        # sibling views, and consumers may mutate what they np.asarray'd
        # (jax.Array.__array__ gives the same independence guarantee)
        return row.copy()

    def __repr__(self) -> str:
        return (f"BatchView(row {self.index} of "
                f"{tuple(self.batch.shape)} {self.batch.dtype})")


class XBatchMeta:
    """Descriptor of a cross-stream batch buffer (rides
    ``buf.extra["nns_xbatch"]``).

    The query serving plane's continuous-batching dispatcher
    (``query/server.py``) coalesces admitted frames from MANY client
    connections into ONE :class:`TensorBuffer` whose tensors are stacked
    along a new leading axis (``(n, *frame_shape)`` per tensor index) so
    the whole bucket traverses the serving pipeline — and the fused
    segment plan — as a single dispatch.  This meta carries what the
    split point (``tensor_query_serversink``) needs to hand each row
    back to its own client, in bucket order:

    - ``extras[i]``: row *i*'s original per-frame ``buf.extra`` dict
      (client id, wire seq, QoS class, restored trace context);
    - ``pts[i]``: row *i*'s presentation timestamp;
    - ``capacity``: the bucket size the batcher collects toward — the
      PAD target for partial-bucket device invokes
      (``JitExecMixin.invoke_stacked``), so exactly one executable
      shape ever compiles regardless of fill.

    ``n`` (the live row count) is ``len(extras)``; stacked tensors may
    carry MORE than ``n`` rows after a padded invoke — rows past ``n``
    are padding and must never be replied.
    """

    __slots__ = ("extras", "pts", "capacity")

    def __init__(self, extras, pts, capacity: int) -> None:
        self.extras = list(extras)
        self.pts = list(pts)
        self.capacity = int(capacity)

    @property
    def n(self) -> int:
        return len(self.extras)

    def __repr__(self) -> str:
        return f"XBatchMeta(n={self.n}, capacity={self.capacity})"


@dataclasses.dataclass
class TensorBuffer:
    """One frame of a tensor stream: N tensor payloads + timestamps.

    ``tensors`` entries are numpy arrays or jax Arrays.  ``metas`` carries an
    optional per-tensor :class:`~nnstreamer_tpu.tensor.meta.TensorMetaInfo`
    for flexible/sparse streams (None for static streams).
    """

    tensors: List[Any] = dataclasses.field(default_factory=list)
    pts: Optional[int] = CLOCK_TIME_NONE
    duration: Optional[int] = CLOCK_TIME_NONE
    metas: Optional[List[Any]] = None
    #: free-form per-buffer metadata (e.g. query client id — reference
    #: tensor_meta.c query_client_id_t).
    extra: dict = dataclasses.field(default_factory=dict)
    #: pool ownership handle when ``tensors`` are zero-copy views into a
    #: :class:`BufferLease` slab (transports attach it so the slab lives
    #: as long as any wrapper/branch still references the frame; the
    #: slab recycles when the last holder drops — see BufferLease)
    lease: Any = dataclasses.field(default=None, repr=False)

    def __post_init__(self):
        # sanitizer hook (one branch per buffer when off): a leased
        # buffer's ndarray payloads are zero-copy views over the pooled
        # slab — register them so writable grants / pool re-issues with
        # live views are caught (analysis/sanitizer.py aliasing checker)
        if _san._ENABLED and self.lease is not None:
            _san.note_views(getattr(self.lease, "_slab", None),
                            self.tensors)

    @property
    def num_tensors(self) -> int:
        return len(self.tensors)

    def np(self, i: int = 0) -> np.ndarray:
        """Materialize tensor ``i`` on host (device sync happens HERE and
        only here).  Under a span-recording tracer the blocking wait on
        a device array — pending async compute + d2h transfer — records
        as a ``device-invoke`` state span (obs/attrib.py): the dispatch
        annotation alone measures only the async enqueue, and the real
        device time would otherwise be misattributed to whichever
        element happened to materialize the output (serialize/decoder)."""
        t = self.tensors[i]
        if isinstance(t, np.ndarray):
            return t
        from ..pipeline import tracing

        if tracing.annotation_active():
            import time as _time

            t0 = _time.monotonic_ns()
            out = np.asarray(t)
            tracing.annotate("device-invoke", t0, _time.monotonic_ns())
            return out
        return np.asarray(t)

    def nbytes(self) -> int:
        total = 0
        for t in self.tensors:
            total += t.nbytes if hasattr(t, "nbytes") else len(t)
        return total

    def with_tensors(self, tensors: Sequence[Any]) -> "TensorBuffer":
        """New buffer with same timestamps/extra but different payloads."""
        return TensorBuffer(tensors=list(tensors), pts=self.pts,
                            duration=self.duration, extra=dict(self.extra))

    def copy(self) -> "TensorBuffer":
        """Shallow copy: a new wrapper with independent ``extra``/``metas``
        containers but the SAME tensor payload handles — no tensor bytes are
        copied, and device arrays stay on device.  A pooled lease is shared
        by reference (tee fan-out: N branches, one payload slab)."""
        return TensorBuffer(tensors=list(self.tensors), pts=self.pts,
                            duration=self.duration,
                            metas=list(self.metas) if self.metas else None,
                            extra=dict(self.extra), lease=self.lease)

    def __repr__(self) -> str:
        shapes = ",".join(str(getattr(t, "shape", "?")) for t in self.tensors)
        return f"TensorBuffer(n={self.num_tensors} shapes=[{shapes}] pts={self.pts})"


SECOND = 1_000_000_000


def frames_to_ns(frame_index: int, rate_num: int, rate_den: int) -> int:
    """PTS of frame N at a given framerate, in ns."""
    if rate_num == 0:
        return 0
    return frame_index * SECOND * rate_den // rate_num
