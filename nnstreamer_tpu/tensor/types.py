"""Tensor type system: dtypes, formats, and limits.

TPU-native re-design of the reference tensor type model
(reference: gst/nnstreamer/include/tensor_typedef.h:133-148 for the dtype
enum, :34-46 for rank/count limits, :222-296 for the info structs).

Differences from the reference, by design:

- dtypes map directly onto numpy/JAX dtypes; ``bfloat16`` is added as a
  first-class type because it is the native MXU dtype on TPU (the reference
  only has IEEE float16 behind an ``enable-float16`` build flag).
- there is no C union of scalar values; Python/numpy scalars are used.
"""

from __future__ import annotations

import enum
from typing import Sequence, Tuple

import numpy as np

import ml_dtypes

#: Maximum rank of a single tensor (reference: tensor_typedef.h:34
#: ``NNS_TENSOR_RANK_LIMIT`` = 8).
TENSOR_RANK_LIMIT = 8

#: Maximum number of tensors carried in one frame of an ``other/tensors``
#: stream (reference: tensor_typedef.h:35 ``NNS_TENSOR_SIZE_LIMIT`` = 16).
TENSOR_SIZE_LIMIT = 16

#: Additional "extra" tensors accessible beyond the base 16 (reference:
#: tensor_typedef.h:44-46 ``NNS_TENSOR_SIZE_EXTRA_LIMIT``).
TENSOR_SIZE_EXTRA_LIMIT = 256


class TensorType(enum.Enum):
    """Element dtype of a tensor stream.

    Reference: ``tensor_type`` enum, tensor_typedef.h:133-148.  String names
    below are the canonical names used in caps/dim strings and must round-trip
    through :func:`TensorType.from_string`.
    """

    INT32 = "int32"
    UINT32 = "uint32"
    INT16 = "int16"
    UINT16 = "uint16"
    INT8 = "int8"
    UINT8 = "uint8"
    FLOAT64 = "float64"
    FLOAT32 = "float32"
    INT64 = "int64"
    UINT64 = "uint64"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"  # TPU-native addition; MXU-preferred dtype.

    @property
    def np_dtype(self) -> np.dtype:
        if self is TensorType.BFLOAT16:
            return np.dtype(ml_dtypes.bfloat16)
        return np.dtype(self.value)

    @property
    def element_size(self) -> int:
        """Bytes per element (reference: tensor_element_size table,
        nnstreamer_plugin_api_util_impl.c:31-35)."""
        return self.np_dtype.itemsize

    @classmethod
    def from_string(cls, name: str) -> "TensorType":
        name = name.strip().lower()
        for t in cls:
            if t.value == name:
                return t
        raise ValueError(f"unknown tensor type {name!r}")

    @classmethod
    def from_np(cls, dtype) -> "TensorType":
        dtype = np.dtype(dtype)
        if dtype == np.dtype(ml_dtypes.bfloat16):
            return cls.BFLOAT16
        return cls.from_string(dtype.name)

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.value


class TensorFormat(enum.Enum):
    """Data format of an ``other/tensors`` stream.

    Reference: ``tensor_format`` enum, tensor_typedef.h:150-157.

    - STATIC: shapes/dtypes fixed at negotiation time (XLA-friendly; the
      common case, and the only format the TPU hot path compiles).
    - FLEXIBLE: every buffer carries a per-tensor meta header describing its
      own shape/dtype (reference ``GstTensorMetaInfo``).
    - SPARSE: COO-style values+indices payload behind the same meta header.
    """

    STATIC = "static"
    FLEXIBLE = "flexible"
    SPARSE = "sparse"

    @classmethod
    def from_string(cls, name: str) -> "TensorFormat":
        name = name.strip().lower()
        for f in cls:
            if f.value == name:
                return f
        raise ValueError(f"unknown tensor format {name!r}")

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.value


#: A tensor dimension, reference convention: ``dim[0]`` is the innermost
#: (fastest-varying) axis — e.g. RGB 640x480 video is ``(3, 640, 480, 1)``.
#: numpy/JAX shape is the reverse of this tuple.
Dimension = Tuple[int, ...]


def dim_parse(dimstr: str) -> Dimension:
    """Parse a ``d1:d2:d3:d4`` dimension string.

    Reference: ``gst_tensor_parse_dimension``
    (nnstreamer_plugin_api_util_impl.c:1081-1118).  Missing trailing
    dimensions are *not* padded here; use :func:`dim_padded` when a fixed
    rank is needed.  ``0`` entries are allowed only in flexible contexts.
    """
    dimstr = dimstr.strip()
    if not dimstr:
        return ()
    parts = dimstr.split(":")
    if len(parts) > TENSOR_RANK_LIMIT:
        raise ValueError(
            f"rank {len(parts)} exceeds limit {TENSOR_RANK_LIMIT}: {dimstr!r}")
    dims = []
    for p in parts:
        p = p.strip()
        v = int(p)
        if v < 0:
            raise ValueError(f"negative dimension in {dimstr!r}")
        dims.append(v)
    return tuple(dims)


def dim_to_string(dim: Sequence[int], *, trim: bool = True) -> str:
    """Print a dimension as ``d1:d2:...``.

    Reference: ``gst_tensor_get_dimension_string``
    (nnstreamer_plugin_api_util_impl.c:1166-1184).  With ``trim`` the
    trailing 1s beyond the first dimension are dropped, matching the
    rank-trimmed printer used in caps.
    """
    dim = list(dim)
    if not dim:
        return ""
    if trim:
        while len(dim) > 1 and dim[-1] == 1:
            dim.pop()
    return ":".join(str(d) for d in dim)


def dim_padded(dim: Sequence[int], rank: int = TENSOR_RANK_LIMIT) -> Dimension:
    """Pad with 1s up to ``rank`` (reference pads unset dims with 1;
    tensor_typedef.h:60-66 discussion)."""
    dim = tuple(dim)
    if len(dim) > rank:
        raise ValueError(f"rank {len(dim)} exceeds {rank}")
    return dim + (1,) * (rank - len(dim))


def dims_equal(a: Sequence[int], b: Sequence[int]) -> bool:
    """Rank-lenient equality: ``3:224:224`` == ``3:224:224:1``.

    Reference: ``gst_tensor_dimension_is_equal``
    (nnstreamer_plugin_api_util_impl.c:1007-1027).
    """
    return dim_padded(a) == dim_padded(b)


def dim_is_static(dim: Sequence[int]) -> bool:
    """True when every entry is > 0 (fully specified shape)."""
    return len(dim) > 0 and all(d > 0 for d in dim)


def dim_element_count(dim: Sequence[int]) -> int:
    """Number of elements for a static dimension (reference:
    gst_tensor_get_element_count, nnstreamer_plugin_api_util_impl.c:1129)."""
    if not dim_is_static(dim):
        raise ValueError(f"dimension {dim} is not static")
    n = 1
    for d in dim:
        n *= d
    return n


def dim_to_np_shape(dim: Sequence[int]) -> Tuple[int, ...]:
    """Reference dim order (innermost-first) → numpy shape (outermost-first)."""
    return tuple(reversed(tuple(dim)))


def np_shape_to_dim(shape: Sequence[int]) -> Dimension:
    """numpy shape → reference dim order."""
    return tuple(reversed(tuple(shape)))
