"""Tensor caps ↔ config conversion.

Equivalent of gst_tensor_caps_from_config / gst_tensors_config_from_structure
(reference: nnstreamer_plugin_api_impl.c:1110-1393) and the caps macros in
tensor_typedef.h:93-128.  The ``other/tensors`` media type covers all three
formats; ``format`` selects static/flexible/sparse.
"""

from __future__ import annotations

from fractions import Fraction

from ..pipeline.caps import ANY_FRAMERATE, Caps, Structure
from .info import TensorsConfig, TensorsInfo
from .types import TensorFormat

TENSORS_MIME = "other/tensors"


def caps_from_config(config: TensorsConfig) -> Caps:
    """Build (possibly non-fixed) caps from a tensors config."""
    fields = {}
    fields["format"] = str(config.format)
    if config.format is TensorFormat.STATIC and config.info.num_tensors > 0:
        fields["num_tensors"] = config.info.num_tensors
        fields["dimensions"] = config.info.dims_string(sep=".")
        fields["types"] = config.info.types_string(sep=".")
    fields["framerate"] = (config.rate if config.rate is not None
                           else ANY_FRAMERATE)
    return Caps([Structure(TENSORS_MIME, fields)])


def config_from_structure(struct: Structure) -> TensorsConfig:
    """Parse a fixed ``other/tensors`` structure into a config."""
    if struct.name != TENSORS_MIME:
        raise ValueError(f"not a tensors structure: {struct.name}")
    fmt = TensorFormat.from_string(str(struct.get("format", "static")))
    info = TensorsInfo()
    dims = struct.get("dimensions")
    types = struct.get("types")
    if dims is not None and types is not None:
        info = TensorsInfo.from_strings(str(dims), str(types))
        num = struct.get("num_tensors")
        if num is not None and int(num) != info.num_tensors:
            raise ValueError(
                f"num_tensors={num} but {info.num_tensors} dims given")
    rate = struct.get("framerate")
    if not isinstance(rate, Fraction):
        rate = None
    return TensorsConfig(info=info, rate=rate, format=fmt)


def config_from_caps(caps: Caps) -> TensorsConfig:
    return config_from_structure(caps.first())


def tensors_template_caps() -> Caps:
    """Pad-template caps accepting any tensor stream."""
    return Caps([
        Structure(TENSORS_MIME, {"format": [str(f) for f in TensorFormat],
                                 "framerate": ANY_FRAMERATE}),
    ])


def static_tensors_caps() -> Caps:
    return Caps([Structure(TENSORS_MIME, {"format": "static",
                                          "framerate": ANY_FRAMERATE})])


def flexible_tensors_caps() -> Caps:
    return Caps([Structure(TENSORS_MIME, {"format": "flexible",
                                          "framerate": ANY_FRAMERATE})])
