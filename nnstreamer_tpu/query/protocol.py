"""Tensor wire protocol for among-device streams.

The transport role of libnnstreamer-edge (reference:
gst/nnstreamer/tensor_query/tensor_query_common.h — TCP default, caps
exchanged as strings; mqtt header layout gst/mqtt/mqttcommon.h:29-61).
TPU-native framing: length-prefixed messages over a stream socket; each DATA
frame carries pts + client id + N tensors, every tensor prefixed with the
framework's 128-byte meta header (nnstreamer_tpu.tensor.meta), so both
static and flexible streams ride the same format.

Message layout (little endian):
  u32 magic 'NNST' | u8 type | u64 client_id | u64 seq | i64 pts
  | i64 epoch_us | u64 trace_id | u64 span_id | i64 origin_us
  | u32 payload_crc | u32 payload_len | payload
``epoch_us`` is the sender's stream-origin wall clock (NTP-aligned unix
epoch µs, 0 = unknown) — the role of the reference mqtt header's
``base_time_epoch`` (gst/mqtt/mqttcommon.h:54) that lets a receiving
pipeline re-base PTS from another device onto its own clock.
``trace_id``/``span_id``/``origin_us`` are the distributed trace
context (obs/span.py TraceContext; all zeros = untraced): the trace id
names the whole distributed trace so client and server spans merge
under one timeline, the span id is the sender-side parent span, and
origin_us is the source stamp (sender wall µs at buffer birth) that
makes cross-process interlatency computable after clock-offset
estimation (obs/clock.py).
``payload_crc`` is CRC-32C of the payload when the sender has the native
tensorwire kernels (0 = unchecked — the pure-Python CRC would serialize
the hot path); receivers verify only nonzero values, so mixed
native/fallback hosts interoperate.
Types: 1=HELLO (payload = caps string utf8 server→client; client→server
the payload may carry a ``qos=<gold|silver|bronze>`` QoS-class
declaration for admission control — query/overload.py), 2=DATA,
3=REPLY, 4=BYE, 5=ERROR (payload = message), 6=PING, 7=PONG, 8=TRACE
(payload = JSON span batch — the server's timeline piggyback, sent
right after a REPLY when the serving pipeline records spans; clients
without a tracer just discard it), 9=SHED (explicit load-shed answer
to a DATA frame refused by admission control: seq echoes the refused
request, payload is the ASCII retry-after hint in milliseconds — an
overloaded or draining server answers every rejected request, no
silent drops), 10=METRICS (payload = JSON metrics-snapshot delta from a
worker process to a telemetry collector — obs/federation.py; seq is the
publisher's push counter, epoch_us the publisher's wall clock at push.
One-way: the collector never replies, so a publisher riding an existing
query connection costs the serving path nothing).
``PING``/``PONG`` are the liveness heartbeat (query/resilience.py): any
peer may send PING at any time; the receiver echoes seq and payload back
as PONG immediately, out of band with DATA/REPLY.  The sender matches
PONGs by seq and derives RTT — the keep-alive role of libnnstreamer-edge's
connection monitoring.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import struct
import time
from typing import Any, List, Optional, Sequence

import numpy as np

from ..pipeline.tracing import annotate, annotation_active, record_copy
from ..tensor.buffer import TensorBuffer, TensorBufferPool
from ..tensor.info import TensorInfo
from ..tensor.meta import META_HEADER_SIZE, TensorMetaInfo

# Wire revision 6 ('NNSV'): + T_METRICS telemetry-federation pushes
# ('NNSU' lacked them, 'NNST' lacked T_SHED/qos, 'NNSS' lacked the
# trace context, 'NNSR' lacked payload_crc, 'NNSQ' also lacked
# epoch_us).  The magic doubles as the version stamp — a peer speaking
# another revision fails immediately with "bad magic" instead of
# desynchronizing the stream (a rev-5 collector would silently drop a
# worker's metric pushes and the fleet view would show a healthy-
# looking hole exactly where the telemetry plane disagreed on dialect).
MAGIC = 0x4E4E5356  # 'NNSV'
HEADER = struct.Struct("<IBQQqqQQqII")
#: upper bound on a wire-declared payload (default 1 GiB, env-overridable):
#: receives reject anything larger before allocating, so a corrupted
#: length field cannot OOM the receiver (a 4K RGB uncompressed frame is
#: ~25 MB; 1 GiB leaves 40x headroom for batched/multi-tensor frames)
MAX_WIRE_PAYLOAD = int(os.environ.get("NNS_MAX_WIRE_PAYLOAD",
                                      str(1 << 30)))

(T_HELLO, T_DATA, T_REPLY, T_BYE, T_ERROR, T_PING, T_PONG, T_TRACE,
 T_SHED, T_METRICS) = 1, 2, 3, 4, 5, 6, 7, 8, 9, 10


def parse_retry_after(payload, default_s: float = 0.1) -> float:
    """The ``T_SHED`` payload contract in ONE place: ASCII retry-after
    milliseconds → seconds, ``default_s`` on an empty or malformed
    payload.  Both reply consumers (QueryConnection's request/response
    path and the llm tier's TokenStreamClient) parse through here so
    the wire format can never silently diverge between them."""
    try:
        return int(bytes(payload or b"") or b"100") / 1e3
    except ValueError:
        return float(default_s)


def parse_hello_tokens(payload) -> dict:
    """Client→server T_HELLO payload grammar: ``;``-separated
    ``key=value`` tokens (``qos=gold;model=resnet``).  Grown from the
    original bare ``qos=<class>`` payload — a single token parses
    identically, so old clients need no change; unknown tokens are kept
    so the grammar can extend without a wire revision.  The ``model``
    token is the fleet router's consistent-hash key
    (fleet/router.py)."""
    out = {}
    for part in bytes(payload or b"").decode("utf-8",
                                             "replace").split(";"):
        key, sep, val = part.partition("=")
        if sep and key:
            out[key.strip()] = val.strip()
    return out


def create_connection(address, timeout=None):
    """``socket.create_connection`` with a loopback self-connect guard.

    A connect retried against a local port with no listener (every
    reconnect/resubscribe loop in this package does exactly that while
    the peer is down) can be assigned that very port as its ephemeral
    local port and "succeed" via TCP simultaneous open — the socket is
    connected to itself, reads back its own writes, and squats on the
    peer's port without SO_REUSEADDR so the real server can't bind when
    it restarts.  Detect it and fail like the refused connect it should
    have been, so retry policies keep backing off.
    """
    sock = socket.create_connection(address, timeout=timeout)
    try:
        self_connected = sock.getsockname() == sock.getpeername()
    except OSError:        # reset under us: let the caller's I/O surface it
        self_connected = False
    if self_connected:
        sock.close()
        raise ConnectionRefusedError(
            f"self-connect to {address[0]}:{address[1]} "
            "(no listener on port)")
    return sock


def shutdown_close(sock) -> None:
    """Tear a socket down so every observer notices immediately.

    ``close()`` alone does not wake a thread blocked in ``recv`` on the
    same fd — the in-flight syscall keeps the kernel socket alive, no FIN
    is sent, and both that thread and the remote peer block forever (it
    also keeps an accepted socket squatting on the listener's port, so a
    restarted server can't bind).  ``shutdown(SHUT_RDWR)`` delivers EOF
    to local readers and a FIN to the peer first; then the fd closes.
    """
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


_CRC_FN = None  # resolved once: callable | False (unavailable)


def _crc_fn():
    """Native CRC-32C, resolved once so the per-message hot path is
    lock-free afterwards.  While a background build of the native lib is
    still running this returns None without caching, so CRC kicks in as
    soon as the build lands."""
    global _CRC_FN
    if _CRC_FN is not None:
        return _CRC_FN or None
    from .. import native

    fn = native.crc32c_fn()   # closure over the loaded lib: no locks/frame
    if fn is not None:
        _CRC_FN = fn
        return fn
    if native._tried:   # definitively unavailable (build failed/absent)
        _CRC_FN = False
    return None


def _payload_crc(payload: bytes) -> int:
    """CRC-32C via the native kernels; 0 (= unchecked) without them."""
    fn = _crc_fn() if payload else None
    if fn is None:
        return 0
    return fn(payload) or 1  # reserve 0 for "absent"


@dataclasses.dataclass
class Message:
    type: int
    client_id: int = 0
    seq: int = 0
    pts: int = 0
    epoch_us: int = 0
    #: distributed trace context (obs/span.py; all zeros = untraced)
    trace_id: int = 0
    span_id: int = 0
    origin_us: int = 0
    #: bytes for control messages; may be a memoryview into a pooled
    #: slab when received via ``recv_msg(sock, pool=...)``
    payload: Any = b""
    #: pool ownership handle for a pooled payload (attach to the
    #: TensorBuffer built from this message so the slab outlives the
    #: zero-copy tensor views)
    lease: Any = dataclasses.field(default=None, repr=False)
    #: received payload CRC (kept so a relay — the edge broker — can
    #: forward the payload without recomputing or re-materializing it)
    crc: int = 0


def pack(msg: Message) -> bytes:
    payload = msg.payload
    if not isinstance(payload, bytes):
        payload = bytes(payload)
    return HEADER.pack(MAGIC, msg.type, msg.client_id, msg.seq,
                       msg.pts, msg.epoch_us, msg.trace_id, msg.span_id,
                       msg.origin_us, _payload_crc(payload),
                       len(payload)) + payload


def tensor_parts(buf: TensorBuffer) -> List[Any]:
    """DATA payload as an iovec: ``[count_u32, meta, view, meta, view…]``.

    Tensor payloads stay zero-copy memoryviews over the source arrays
    (device arrays materialize on host here — that is a transfer, not a
    framing copy; a non-contiguous host array pays one compaction copy,
    reported via tracing.record_copy).  Only the 4-byte count and the
    128-byte per-tensor meta headers are fresh bytes.
    """
    parts: List[Any] = [struct.pack("<I", buf.num_tensors)]
    for i in range(buf.num_tensors):
        arr = buf.np(i)
        meta = TensorMetaInfo.from_info(TensorInfo.from_np(arr))
        parts.append(meta.to_bytes())
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
            record_copy(arr.nbytes)
        parts.append(arr.reshape(-1).view(np.uint8).data)
    return parts


def _parts_crc(parts: Sequence[Any]) -> int:
    """Incremental CRC-32C over the iovec (native kernels chain via the
    seed argument; 0 = unchecked without them)."""
    fn = _crc_fn()
    if fn is None:
        return 0
    crc = 0
    for p in parts:
        crc = fn(p, crc)
    return crc or 1  # reserve 0 for "absent"


def sendmsg_all(sock: socket.socket, parts: Sequence[Any]) -> None:
    """``sendall`` for an iovec: one ``socket.sendmsg`` gathers every
    part in kernel space — no ``b"".join`` flattening — looping on
    partial sends."""
    parts = [p if isinstance(p, (bytes, memoryview)) else memoryview(p)
             for p in parts]
    total = sum(len(p) for p in parts)
    sent = 0
    while sent < total:
        n = sock.sendmsg(parts)
        sent += n
        if sent >= total:
            return
        # partial send: drop whole parts, slice the straddling one
        while n > 0 and n >= len(parts[0]):
            n -= len(parts[0])
            parts.pop(0)
        if n:
            head = parts[0]
            if isinstance(head, bytes):
                head = memoryview(head)
            parts[0] = head[n:]


def send_tensors(sock: socket.socket, msg_type: int, buf: TensorBuffer,
                 client_id: int = 0, seq: int = 0, pts: int = 0,
                 epoch_us: int = 0, trace_id: int = 0, span_id: int = 0,
                 origin_us: int = 0) -> None:
    """Scatter-gather DATA/REPLY send: header + count + per-tensor
    (meta, payload view) as one ``sendmsg`` iovec.  The tensor payload
    bytes are handed to the kernel straight from the source arrays —
    the serialize path's only fresh bytes are the wire header, the
    count word, and the 128-byte metas."""
    t0 = time.monotonic_ns() if annotation_active() else 0
    parts = tensor_parts(buf)
    plen = sum(len(p) if isinstance(p, bytes) else p.nbytes for p in parts)
    header = HEADER.pack(MAGIC, msg_type, client_id, seq, pts, epoch_us,
                         trace_id, span_id, origin_us,
                         _parts_crc(parts), plen)
    record_copy(len(header))   # header+metas are the copy budget
    record_copy(4 + META_HEADER_SIZE * buf.num_tensors)
    if t0:
        # framing/CRC is serialize; the sendmsg below is transfer time
        # and stays in the enclosing element span (wire)
        annotate("serialize", t0, time.monotonic_ns())
    sendmsg_all(sock, [header] + parts)


def encode_tensors(buf: TensorBuffer) -> bytes:
    """Serialize all tensors with per-tensor meta headers into one
    contiguous blob.  This MATERIALIZES every payload byte — transports
    on the hot path use :func:`tensor_parts` / :func:`send_tensors`
    instead; this stays for single-blob consumers (mqtt, files) and
    reports itself to the copy tracer."""
    parts = tensor_parts(buf)
    blob = b"".join(bytes(p) if not isinstance(p, bytes) else p
                    for p in parts)
    record_copy(len(blob))
    return blob


def decode_tensors(payload) -> List[np.ndarray]:
    """Zero-copy decode: tensors are views into ``payload`` (bytes or a
    pooled-slab memoryview).  Views are read-only — pooled payloads are
    shared (tee contract); attach the message's lease to the
    TensorBuffer that carries them.  ``writeable=False`` survives numpy
    view/reshape derivation, so downstream transform/decoder reshapes
    stay non-writable; under the sanitizer (``NNS_DEBUG=1``) a write
    attempt raises a contract-naming AliasingError instead of numpy's
    bare read-only ValueError (analysis/sanitizer.py guard_readonly)."""
    t0 = time.monotonic_ns() if annotation_active() else 0
    (n,) = struct.unpack_from("<I", payload, 0)
    off = 4
    tensors = []
    from ..analysis import sanitizer as _san
    from ..tensor.types import dim_to_np_shape

    guard = _san._ENABLED
    for _ in range(n):
        meta = TensorMetaInfo.from_bytes(payload[off:off + META_HEADER_SIZE])
        off += META_HEADER_SIZE
        size = meta.data_size
        raw = np.frombuffer(payload, np.uint8, count=size, offset=off)
        off += size
        arr = (raw.view(meta.dtype.np_dtype)
               .reshape(dim_to_np_shape(meta.dims)))
        if arr.flags.writeable:
            arr.flags.writeable = False
        if guard:
            arr = _san.guard_readonly(arr)
        tensors.append(arr)
    if t0:
        annotate("serialize", t0, time.monotonic_ns())
    return tensors


def send_msg(sock: socket.socket, msg: Message) -> None:
    sock.sendall(pack(msg))


def send_msg_zc(sock: socket.socket, msg: Message) -> None:
    """Relay a received message without flattening its payload: header
    and payload view go out as one ``sendmsg`` iovec, reusing the
    already-verified CRC (the edge broker's fan-out hot path)."""
    payload = msg.payload
    if isinstance(payload, bytes):
        sock.sendall(pack(msg))
        return
    header = HEADER.pack(MAGIC, msg.type, msg.client_id, msg.seq,
                         msg.pts, msg.epoch_us, msg.trace_id,
                         msg.span_id, msg.origin_us, msg.crc,
                         len(payload))
    sendmsg_all(sock, [header, payload])


def recv_msg(sock: socket.socket,
             pool: Optional[TensorBufferPool] = None) -> Optional[Message]:
    """Receive one message.  With ``pool``, DATA/REPLY payloads land via
    ``recv_into`` in a recycled :class:`BufferLease` slab (zero
    intermediate chunk list, zero ``b"".join``) and ``msg.payload`` is a
    memoryview with ``msg.lease`` holding the slab."""
    # the header's first byte is the only point where a socket timeout
    # is benign (idle connection on a bounded-send socket —
    # query/server.py sets one so a non-draining client cannot wedge
    # the pipeline thread in reply()); it propagates as TimeoutError
    # for the caller to retry.  Any LATER timeout is a mid-message
    # stall: the stream is desynced and the peer is treated as gone.
    hdr = _recv_exact(sock, HEADER.size, idle_ok=True)
    if hdr is None:
        return None
    (magic, typ, cid, seq, pts, epoch, trace_id, span_id, origin_us,
     crc, plen) = HEADER.unpack(hdr)
    if magic != MAGIC:
        raise ValueError(f"bad magic 0x{magic:08x}")
    if plen > MAX_WIRE_PAYLOAD:
        # sanity-bound the wire-declared length BEFORE allocating: a
        # corrupted header (chaos 'corrupt' mode / bit-flip / malicious
        # peer) must fail like a CRC mismatch, not as an up-to-4 GiB
        # upfront bytearray allocation in pool.acquire
        raise ValueError(
            f"payload length {plen} exceeds wire bound "
            f"{MAX_WIRE_PAYLOAD} (corrupt header?)")
    lease = None
    if not plen:
        payload = b""
    elif pool is not None and typ in (T_DATA, T_REPLY):
        lease = pool.acquire(plen)
        payload = lease.memory()
        if not _recv_exact_into(sock, payload):
            lease.release()
            return None
    else:
        payload = _recv_exact(sock, plen)
        if payload is None:
            return None
    if crc and plen:
        fn = _crc_fn()
        if fn is not None:
            got = fn(payload) or 1
            if got != crc:
                if lease is not None:
                    lease.release()
                raise ValueError(
                    f"payload CRC mismatch: frame seq={seq} declared "
                    f"0x{crc:08x}, computed 0x{got:08x} (corrupt stream)")
    return Message(type=typ, client_id=cid, seq=seq, pts=pts,
                   epoch_us=epoch, trace_id=trace_id, span_id=span_id,
                   origin_us=origin_us, payload=payload, lease=lease,
                   crc=crc)


def _recv_exact(sock: socket.socket, n: int,
                idle_ok: bool = False) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(n - got)
        except socket.timeout:
            if idle_ok and not chunks:
                raise          # idle timeout before any byte: retryable
            return None        # mid-read stall: desynced zombie peer
        except (ConnectionResetError, OSError):
            return None
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _recv_exact_into(sock: socket.socket, mv: memoryview) -> bool:
    """Fill ``mv`` completely from the socket (True on success)."""
    got = 0
    n = len(mv)
    while got < n:
        try:
            k = sock.recv_into(mv[got:])
        except socket.timeout:
            return False       # mid-payload stall: desynced zombie peer
        except (ConnectionResetError, OSError):
            return False
        if not k:
            return False
        got += k
    return True
