"""Overload protection for the query serving plane: QoS classes,
token-bucket + watermark admission control, and hysteretic load
shedding.

The among-device layer (tensor_query_*) assumed a well-behaved client
population; the PR 6 soak harness proved the opposite — 64 loopback
clients saturate the single-threaded serving path, and an unbounded
``QueryServer.incoming`` absorbed the excess as unbounded memory growth
and unbounded latency.  This module makes overload an *explicit,
measurable* degradation instead:

- **QoS classes** — every connection carries one of ``gold`` /
  ``silver`` / ``bronze`` (negotiated in the ``T_HELLO`` capability
  handshake as a ``qos=<class>`` payload; unnegotiated connections
  default to ``silver``).  Clients that never set an explicit class
  inherit one from the loadgen's ``buf.extra["nns_class"]`` tagging via
  :func:`qos_of_class`.
- **Admission control** — :class:`AdmissionController` decides
  admit-or-shed per request from (a) an optional :class:`TokenBucket`
  capacity limit and (b) a pluggable :class:`ShedPolicy` driven by the
  PR 5 gauges (queue depth, p99 proctime).  The decision reads the
  message header only — an overloaded request is refused BEFORE its
  tensors are deserialized into pooled slabs.
- **Load shedding** — a shed is answered with an explicit ``T_SHED``
  wire reply carrying a retry-after hint; the client maps it into the
  PR 1 fallback machinery (:class:`ShedError` is a ``ConnectionError``
  so ``fallback=error|passthrough|drop`` all apply) WITHOUT tripping
  circuit breakers — a shed proves the server is alive and protecting
  itself; it is not a failure.
- **Hysteresis** — the default :class:`WatermarkShedPolicy` arms
  shedding per class at a high queue-depth watermark and disarms at a
  low one (like the PR 6 burn-rate evaluator's arming), so the
  shed/admit boundary does not flap at the watermark.  Bronze sheds
  first, gold last.

Depends only on the stdlib + the sanitizer lock wrappers so every
transport layer can use it without cycles.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple

from ..analysis.sanitizer import make_lock

#: QoS classes ordered by privilege: bronze sheds first, gold last.
QOS_CLASSES: Tuple[str, ...] = ("gold", "silver", "bronze")
#: shed priority rank: higher rank sheds earlier
QOS_RANK: Dict[str, int] = {"gold": 0, "silver": 1, "bronze": 2}
#: class an unnegotiated connection gets
DEFAULT_QOS = "silver"

#: loadgen/request-class tags that imply a QoS class (the
#: ``buf.extra["nns_class"]`` vocabulary the PR 6 loadgen already
#: writes); identity for the QoS names themselves
_CLASS_ALIASES: Dict[str, str] = {
    "gold": "gold", "silver": "silver", "bronze": "bronze",
    "interactive": "gold", "realtime": "gold",
    "default": "silver",
    "batch": "bronze", "bulk": "bronze", "background": "bronze",
}


def qos_of_class(name: Optional[str]) -> Optional[str]:
    """QoS class implied by a request-class tag, or None when the tag
    carries no QoS meaning (the connection then stays unnegotiated and
    the server applies :data:`DEFAULT_QOS`)."""
    if not name:
        return None
    return _CLASS_ALIASES.get(str(name).lower())


#: cross-stream batching residency budgets, as a fraction of the
#: bucket's ``batch-timeout-ms``: how long a frame of each class may sit
#: in a COLLECTING bucket waiting for peers before the bucket must
#: dispatch.  Gold waits a quarter of the configured deadline, bronze
#: the whole of it — so a gold frame landing in a bucket that bronze
#: traffic opened pulls the dispatch deadline IN (the bucket fires at
#: the minimum over resident frames' budgets) and never waits out a
#: bronze-sized fill window.  Admission (shed-or-admit) stays a separate,
#: earlier decision — budgets only shape who waits for whom AFTER
#: admission.
XBATCH_BUDGET_FACTOR: Dict[str, float] = {
    "gold": 0.25, "silver": 0.5, "bronze": 1.0}


def bucket_budget(qos: Optional[str], timeout_s: float) -> float:
    """Residency budget (seconds) of one admitted frame in a collecting
    cross-stream bucket: the configured coalesce deadline scaled by the
    frame's QoS class (:data:`XBATCH_BUDGET_FACTOR`).  ``timeout_s <= 0``
    (greedy batching — dispatch whatever is queued, never wait) returns
    0.0 for every class."""
    if timeout_s <= 0:
        return 0.0
    return timeout_s * XBATCH_BUDGET_FACTOR.get(qos or DEFAULT_QOS, 1.0)


class ShedError(ConnectionError):
    """The server answered ``T_SHED``: the request was refused by
    admission control, NOT failed.  ``retry_after_s`` is the server's
    hint for when capacity should exist again.

    Subclasses :class:`ConnectionError` so the tensor_query_client
    fallback machinery (``fallback=error|passthrough|drop``) applies
    unchanged — but resilience code must catch it FIRST and keep
    circuit breakers closed: a shed proves liveness.
    """

    def __init__(self, retry_after_s: float = 0.1, qos: str = "",
                 message: str = "") -> None:
        self.retry_after_s = float(retry_after_s)
        self.qos = qos
        super().__init__(
            message or f"request shed (qos={qos or '?'}, "
                       f"retry after {self.retry_after_s:.3f}s)")


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill, ``burst`` cap.

    ``take()`` is the admission primitive: True consumes one token;
    False returns how long until one exists (the retry-after hint).
    O(1), one lock, refill computed lazily from the monotonic clock
    (injectable for tests).
    """

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if rate <= 0:
            raise ValueError("rate must be > 0 tokens/s")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(1.0, rate / 4.0))
        self._clock = clock
        self._tokens = self.burst
        self._t_last = clock()
        self._lock = make_lock("query.overload")

    def take(self, n: float = 1.0) -> Tuple[bool, float]:
        """Try to consume ``n`` tokens.  Returns ``(True, 0.0)`` on
        success or ``(False, wait_s)`` with the time until ``n`` tokens
        will have refilled."""
        now = self._clock()
        with self._lock:
            self._tokens = min(
                self.burst, self._tokens + (now - self._t_last) * self.rate)
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.rate


class ShedPolicy:
    """Decide admit-or-shed for one request.  Subclass hook for
    alternative shedding strategies (CoDel-style sojourn targets,
    per-class token buckets, cost-based admission…).

    ``decide(qos, depth, capacity)`` returns ``None`` to admit or a
    retry-after hint in seconds to shed.  Called on the per-connection
    reader thread for every DATA frame — keep it O(1).
    """

    def decide(self, qos: str, depth: int,
               capacity: int) -> Optional[float]:
        raise NotImplementedError


class WatermarkShedPolicy(ShedPolicy):
    """Queue-depth watermarks with per-class hysteresis, optionally
    compounded by a p99-latency signal.

    Each QoS class has an ARM watermark (fraction of queue capacity);
    when the queue depth reaches it, that class sheds until depth falls
    back under the DISARM watermark (default: half the arm point) —
    the same arm/disarm shape as the PR 6 burn-rate evaluator, so the
    admit/shed boundary cannot flap once per frame at the threshold.
    Bronze arms lowest (sheds first), gold highest (sheds last).

    ``p99_us_fn`` (optional) supplies a latency signal — e.g. a lazy
    read of the PR 5 ``nns_element_proctime_us`` histogram's p99 or the
    server's service histogram.  While it exceeds ``p99_threshold_us``,
    bronze-tier traffic sheds even below its depth watermark (latency
    overload can precede queue growth when requests are large); the
    latch releases at 80 % of the threshold.
    """

    #: arm watermark per class, as a fraction of queue capacity
    ARM = {"gold": 0.90, "silver": 0.70, "bronze": 0.45}

    def __init__(self, arm: Optional[Dict[str, float]] = None,
                 disarm_ratio: float = 0.5,
                 retry_after_s: float = 0.1,
                 p99_us_fn: Optional[Callable[[], float]] = None,
                 p99_threshold_us: float = 0.0) -> None:
        self.arm = dict(arm or self.ARM)
        self.disarm_ratio = float(disarm_ratio)
        self.retry_after_s = float(retry_after_s)
        self.p99_us_fn = p99_us_fn
        self.p99_threshold_us = float(p99_threshold_us)
        self._armed: Dict[str, bool] = {c: False for c in self.arm}
        self._p99_armed = False
        self._lock = make_lock("query.overload")

    def _retry_after(self, qos: str) -> float:
        # lower tiers wait longer before retrying: the backoff itself
        # is priority-ordered, so recovering capacity reaches gold first
        return self.retry_after_s * (1 + QOS_RANK.get(qos, 1))

    def decide(self, qos: str, depth: int,
               capacity: int) -> Optional[float]:
        qos = qos if qos in self.arm else DEFAULT_QOS
        cap = max(1, int(capacity))
        frac = depth / cap
        with self._lock:
            armed = self._armed.get(qos, False)
            arm_at = self.arm.get(qos, 0.7)
            if armed:
                if frac <= arm_at * self.disarm_ratio:
                    self._armed[qos] = armed = False
            elif frac >= arm_at:
                self._armed[qos] = armed = True
            if armed:
                return self._retry_after(qos)
            # latency signal: sheds the bronze tier ahead of queue
            # growth; hysteretic like the depth latch
            if self.p99_us_fn is not None and self.p99_threshold_us > 0 \
                    and QOS_RANK.get(qos, 1) >= QOS_RANK["bronze"]:
                try:
                    p99 = float(self.p99_us_fn())
                except Exception:   # noqa: BLE001 — dead gauge: no signal
                    p99 = 0.0
                if self._p99_armed:
                    if p99 < 0.8 * self.p99_threshold_us:
                        self._p99_armed = False
                elif p99 > self.p99_threshold_us:
                    self._p99_armed = True
                if self._p99_armed:
                    return self._retry_after(qos)
        return None


class AdmissionController:
    """Admit-or-shed decisions for one serving endpoint.

    Composes the two admission signals in cost order: the token bucket
    (pure arithmetic) runs first, the shed policy (reads the queue
    depth gauge) second.  ``admit(qos, depth, capacity)`` returns
    ``None`` to admit or a retry-after hint in seconds.

    While :meth:`start_drain` is in effect EVERYTHING sheds with a
    retry-after sized to the drain deadline — the wire-visible half of
    graceful drain (clients route away instead of timing out).
    """

    def __init__(self, policy: Optional[ShedPolicy] = None,
                 bucket: Optional[TokenBucket] = None) -> None:
        self.policy = policy if policy is not None else WatermarkShedPolicy()
        self.bucket = bucket
        self._drain_until: Optional[float] = None
        self._drain_clock: Callable[[], float] = time.monotonic

    def start_drain(self, deadline_s: float,
                    clock: Callable[[], float] = time.monotonic) -> None:
        # keep the clock: admit() must compute the remaining drain with
        # the SAME clock or an injected one would yield nonsense hints
        self._drain_clock = clock
        self._drain_until = clock() + max(0.0, deadline_s)

    @property
    def draining(self) -> bool:
        return self._drain_until is not None

    def admit(self, qos: str, depth: int,
              capacity: int) -> Optional[float]:
        drain_until = self._drain_until
        if drain_until is not None:
            # drain retry-after: clients should come back after the
            # replacement had time to take over (≥ remaining drain)
            return max(0.1, drain_until - self._drain_clock() + 0.5)
        # policy first, bucket second: a policy-shed request must not
        # burn a token, or shed floods would starve the capacity the
        # bucket is supposed to guarantee the admitted tiers
        verdict = self.policy.decide(qos, depth, capacity)
        if verdict is not None:
            return verdict
        if self.bucket is not None:
            ok, wait = self.bucket.take()
            if not ok:
                return max(wait, 0.01)
        return None
