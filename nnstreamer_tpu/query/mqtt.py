"""MQTT pub/sub elements: broker-based loose coupling between pipelines.

Parity with the reference's mqttsink/mqttsrc (gst/mqtt/mqttsink.c,
mqttsrc.c over paho MQTTAsync):

- **Protocol**: a from-scratch MQTT 3.1.1 client (CONNECT/CONNACK,
  QoS-0 PUBLISH, SUBSCRIBE/SUBACK, PINGREQ, DISCONNECT) speaking the
  standard wire format, so it interoperates with any external broker
  (mosquitto etc.) exactly like the reference's paho link — this image
  ships neither paho nor a broker, so the protocol layer is in-tree and
  :class:`MqttBroker` provides the localhost broker the reference's
  tests gate on (tests/check_broker.sh).
- **Message layout**: the reference's 1024-byte ``GstMQTTMessageHdr``
  (mqttcommon.h:29-61) prepended to the concatenated memory blocks:
  num_mems + 16 memory sizes + base/sent NTP-epoch times (µs) + duration/
  dts/pts + a 512-byte caps string, zero-padded to 1024 bytes.
- **Timestamp sync**: base_time_epoch embeds the publisher's stream-origin
  wall clock (NTP-aligned when ``ntp-host`` is set); ``mqttsrc
  sync-pts=true`` re-bases incoming PTS onto the subscriber's clock
  (Documentation/synchronization-in-mqtt-elements.md).
"""

from __future__ import annotations

import queue as _queue
import socket
import struct
import threading
import time
from fractions import Fraction
from typing import Dict, List, Optional, Set

import numpy as np

from ..analysis.sanitizer import make_lock
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                tensors_template_caps)
from ..utils.log import logger

# -- GstMQTTMessageHdr (mqttcommon.h:29-61) ---------------------------------
HDR_LEN = 1024                 # GST_MQTT_LEN_MSG_HDR
MAX_CAPS_LEN = 512             # GST_MQTT_MAX_LEN_GST_CAPS_STR
MAX_NUM_MEMS = 16              # GST_MQTT_MAX_NUM_MEMS
CLOCK_NONE = (1 << 64) - 1     # GST_CLOCK_TIME_NONE
# natural C alignment: u32 num_mems, 4 pad, 16*u64 sizes, 2*i64 epochs,
# 3*u64 clock times, 512 caps chars; zero-padded to 1024
_HDR_FMT = "<I4x16QqqQQQ512s"
_HDR_PAD = HDR_LEN - struct.calcsize(_HDR_FMT)


def pack_header(sizes: List[int], base_epoch_us: int, sent_epoch_us: int,
                duration: Optional[int], dts: Optional[int],
                pts: Optional[int], caps_str: str, ctx=None) -> bytes:
    if len(sizes) > MAX_NUM_MEMS:
        raise ValueError(f"mqtt: {len(sizes)} memories > {MAX_NUM_MEMS}")
    caps_b = caps_str.encode()
    if len(caps_b) >= MAX_CAPS_LEN:
        raise ValueError(f"mqtt: caps string {len(caps_b)}B >= "
                         f"{MAX_CAPS_LEN}B limit (mqttcommon.h)")
    padded = list(sizes) + [0] * (MAX_NUM_MEMS - len(sizes))
    hdr = struct.pack(_HDR_FMT, len(sizes), *padded,
                      base_epoch_us, sent_epoch_us,
                      CLOCK_NONE if duration is None else duration,
                      CLOCK_NONE if dts is None else dts,
                      CLOCK_NONE if pts is None else pts, caps_b)
    if ctx is not None and ctx.trace_id:
        # trace context rides the zero-pad region after the reference
        # fields (obs/span.py trailer blob, self-identifying by magic):
        # a context-unaware reference peer sees it as padding
        from ..obs.span import pack_ctx_trailer

        blob = pack_ctx_trailer(ctx)
        return hdr + blob + b"\x00" * (_HDR_PAD - len(blob))
    return hdr + b"\x00" * _HDR_PAD


def header_trace_ctx(blob: bytes):
    """Trace context stashed in the header's pad region by
    :func:`pack_header`, or None (reference-compatible zero padding)."""
    from ..obs.span import TRAILER_SIZE, unpack_ctx_trailer

    base = struct.calcsize(_HDR_FMT)
    if len(blob) < base + TRAILER_SIZE:
        return None
    return unpack_ctx_trailer(blob, base + TRAILER_SIZE)


def unpack_header(blob: bytes):
    vals = struct.unpack_from(_HDR_FMT, blob)
    num = vals[0]
    sizes = list(vals[1:1 + MAX_NUM_MEMS])[:num]
    base_us, sent_us, duration, dts, pts = vals[17:22]
    caps_str = vals[22].split(b"\x00", 1)[0].decode(errors="replace")
    none = lambda v: None if v == CLOCK_NONE else v  # noqa: E731
    return (sizes, base_us, sent_us, none(duration), none(dts), none(pts),
            caps_str)


# -- minimal MQTT 3.1.1 wire ------------------------------------------------

def _remaining_len(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _read_packet(sock: socket.socket):
    """Returns (packet_type, payload bytes) or None on EOF."""
    h = sock.recv(1)
    if not h:
        return None
    ptype = h[0]
    mult, n = 1, 0
    while True:
        b = sock.recv(1)
        if not b:
            return None
        n += (b[0] & 0x7F) * mult
        if not b[0] & 0x80:
            break
        mult *= 128
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            return None
        data += chunk
    return ptype, data


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


class MqttClient:
    """Blocking MQTT 3.1.1 client, QoS 0 (the reference publishes QoS-0
    data frames the same way).

    ``keepalive`` (seconds) is a REAL keepalive: it is declared in
    CONNECT (so a spec-conforming broker may drop us at 1.5× silence)
    and honored by a background pinger sending PINGREQ every
    ``keepalive/2`` seconds — the liveness role the reference delegates
    to paho's keepAliveInterval (mqttsink.c).  0 disables both (the old
    behavior, still used by one-shot discovery reads)."""

    def __init__(self, host: str, port: int, client_id: str,
                 timeout: float = 5.0, keepalive: int = 30,
                 publish_only: bool = False) -> None:
        self._publish_only = bool(publish_only)
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self.keepalive = max(0, int(keepalive))
        var = (_mqtt_str("MQTT") + bytes([4])    # protocol level 3.1.1
               + bytes([0x02])                   # clean session
               + struct.pack(">H", self.keepalive))
        payload = _mqtt_str(client_id)
        pkt = bytes([0x10]) + _remaining_len(len(var) + len(payload)) \
            + var + payload
        self._sock.sendall(pkt)
        resp = _read_packet(self._sock)
        if resp is None or resp[0] >> 4 != 2 or resp[1][1] != 0:
            raise ConnectionError(f"mqtt: CONNACK refused: {resp}")
        self._sock.settimeout(None)
        self._pid = 0
        self._lock = make_lock("query.send")   # one writer at a time on
        #                                        the broker stream
        self._early: List = []   # PUBLISHes delivered before SUBACK
        self._closed = False
        self._ping_stop = threading.Event()
        self.pings_sent = 0
        if self.keepalive:
            threading.Thread(target=self._ping_loop, daemon=True,
                             name=f"mqtt-keepalive:{client_id}").start()

    def _ping_loop(self) -> None:
        # half the declared interval keeps us safely inside the broker's
        # 1.5×-keepalive disconnect window even if one PINGREQ is lost
        while not self._ping_stop.wait(self.keepalive / 2.0):
            try:
                with self._lock:
                    self._sock.sendall(bytes([0xC0, 0]))  # PINGREQ
                self.pings_sent += 1
                if self._publish_only:
                    self._drain_unread()
            except OSError:
                return   # link gone; reader surfaces the disconnect

    def _drain_unread(self) -> None:
        """Discard pending inbound bytes (PINGRESPs and stray packets) on
        a publish-only link: nothing else ever reads this socket, so
        without this the receive buffer eventually fills and the broker's
        send side wedges.  Never used when a reader consumes the stream —
        the two would steal each other's bytes."""
        import select

        while True:
            r, _, _ = select.select([self._sock], [], [], 0)
            if not r:
                return
            try:
                if not self._sock.recv(4096):
                    return   # EOF: the ping send will surface the close
            except OSError:
                return

    @staticmethod
    def _split_publish(ptype: int, data: bytes):
        """(topic, packet_id|None, payload) of a PUBLISH packet — QoS>0
        carries a 2-byte packet id between topic and payload."""
        qos = (ptype >> 1) & 3
        tlen = struct.unpack(">H", data[:2])[0]
        topic = data[2:2 + tlen].decode()
        off = 2 + tlen
        pid = None
        if qos:
            pid = struct.unpack(">H", data[off:off + 2])[0]
            off += 2
        return topic, pid, data[off:]

    def publish(self, topic: str, payload: bytes,
                retain: bool = False) -> None:
        var = _mqtt_str(topic)   # QoS 0: no packet id
        with self._lock:
            self._sock.sendall(bytes([0x31 if retain else 0x30])
                               + _remaining_len(len(var) + len(payload))
                               + var + payload)

    def subscribe(self, topic: str) -> None:
        self._pid += 1
        var = struct.pack(">H", self._pid)
        payload = _mqtt_str(topic) + bytes([0])  # requested QoS 0
        with self._lock:
            self._sock.sendall(bytes([0x82])
                               + _remaining_len(len(var) + len(payload))
                               + var + payload)
        # the broker may deliver matching (e.g. retained) PUBLISHes before
        # the SUBACK — buffer them for recv_publish instead of failing
        while True:
            resp = _read_packet(self._sock)
            if resp is None:
                raise ConnectionError("mqtt: connection lost before SUBACK")
            if resp[0] >> 4 == 9:
                return
            if resp[0] >> 4 == 3:
                topic_, _pid, body = self._split_publish(*resp)
                self._early.append((topic_, body))

    def recv_publish(self):
        """Blocks for the next PUBLISH; returns (topic, payload) or None
        on disconnect/close."""
        if self._early:
            return self._early.pop(0)
        while True:
            try:
                pkt = _read_packet(self._sock)
            except OSError:
                return None      # closed under us (element stop())
            if pkt is None:
                return None
            ptype, data = pkt
            if ptype >> 4 == 3:        # PUBLISH
                topic, pid, body = self._split_publish(ptype, data)
                if pid is not None:    # QoS 1 delivery → PUBACK
                    with self._lock:
                        self._sock.sendall(
                            bytes([0x40, 2]) + struct.pack(">H", pid))
                return topic, body
            if ptype >> 4 == 13:       # PINGRESP (keepalive answer)
                continue

    def close(self) -> None:
        self._closed = True
        self._ping_stop.set()
        try:
            with self._lock:
                self._sock.sendall(bytes([0xE0, 0]))  # DISCONNECT
            self._sock.close()
        except OSError:
            pass


def fetch_retained_record(host: str, port: int, topic: str,
                          timeout: float, client_id: str):
    """One-shot hybrid-discovery read: connect to the broker, subscribe
    to ``topic``, and wait (bounded by ``timeout`` — covering the
    SUBACK handshake too, so a wedged broker cannot hang the caller
    indefinitely) for the retained record.  Returns the payload bytes,
    or None when the broker has no record.  Shared by edge_src and
    tensor_query_client HYBRID discovery (one copy of the
    subscribe/wait/parse sequence to keep in sync)."""
    client = MqttClient(host, port, client_id, keepalive=0)
    try:
        client._sock.settimeout(timeout)
        client.subscribe(topic)
        got = client.recv_publish()
        return got[1] if got else None
    finally:
        client.close()


class MqttBroker:
    """Minimal in-process MQTT 3.1.1 broker (QoS 0, exact-topic match) —
    the localhost broker the reference's MQTT tests gate on
    (tests/check_broker.sh), self-contained so no mosquitto is needed."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.host, self.port = host, self._sock.getsockname()[1]
        self._sock.listen(16)
        self._subs: Dict[str, Set[socket.socket]] = {}
        self._locks: Dict[socket.socket, threading.Lock] = {}
        self._retained: Dict[str, bytes] = {}
        self._lock = make_lock("query.registry")
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True,
                         name="mqtt-broker").start()

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        topics: List[str] = []
        try:
            pkt = _read_packet(conn)
            if pkt is None or pkt[0] >> 4 != 1:
                return
            conn.sendall(bytes([0x20, 2, 0, 0]))  # CONNACK accepted
            self._locks[conn] = make_lock("query.send")
            while not self._stop.is_set():
                pkt = _read_packet(conn)
                if pkt is None:
                    return
                ptype, data = pkt
                code = ptype >> 4
                if code == 8:       # SUBSCRIBE
                    pid = data[:2]
                    tlen = struct.unpack(">H", data[2:4])[0]
                    topic = data[4:4 + tlen].decode()
                    topics.append(topic)
                    # take this conn's send lock BEFORE releasing the
                    # broker lock: a concurrent publisher snapshots the
                    # new subscriber and then needs the send lock, so it
                    # cannot interleave with (or overtake) the
                    # SUBACK+retained writes (same handoff as
                    # edge.EdgeBroker)
                    with self._lock:
                        self._subs.setdefault(topic, set()).add(conn)
                        retained = self._retained.get(topic)
                        slock = self._locks.get(conn)
                        if slock is not None:
                            slock.acquire()
                    try:
                        conn.sendall(bytes([0x90, 3]) + pid + bytes([0]))
                        if retained is not None:
                            body = _mqtt_str(topic) + retained
                            conn.sendall(bytes([0x31])
                                         + _remaining_len(len(body)) + body)
                    finally:
                        if slock is not None:
                            slock.release()
                elif code == 3:     # PUBLISH → fan out (downgraded to QoS 0)
                    topic, pid, body = MqttClient._split_publish(ptype, data)
                    if pid is not None:   # QoS-1 sender needs a PUBACK
                        with self._locks[conn]:   # see PINGREQ below
                            conn.sendall(bytes([0x40, 2])
                                         + struct.pack(">H", pid))
                    if ptype & 0x01:      # retain flag
                        with self._lock:
                            if body:
                                self._retained[topic] = body
                            else:
                                # MQTT 3.1.1: empty retained payload
                                # CLEARS the retained message
                                self._retained.pop(topic, None)
                    out = _mqtt_str(topic) + body
                    with self._lock:
                        subs = [(s, self._locks.get(s))
                                for s in self._subs.get(topic, ())]
                    pkt_out = bytes([0x30]) + _remaining_len(len(out)) + out
                    for s, lk in subs:
                        try:
                            if lk is None:
                                s.sendall(pkt_out)
                            else:
                                with lk:
                                    s.sendall(pkt_out)
                        except OSError:
                            with self._lock:
                                self._subs.get(topic, set()).discard(s)
                elif code == 12:    # PINGREQ
                    # under the conn's send lock: this client may also be
                    # a subscriber receiving a concurrent fanout, and a
                    # PINGRESP spliced into a partially-sent PUBLISH
                    # would corrupt its stream
                    with self._locks[conn]:
                        conn.sendall(bytes([0xD0, 0]))
                elif code == 14:    # DISCONNECT
                    return
        finally:
            with self._lock:
                for t in topics:
                    self._subs.get(t, set()).discard(conn)
                self._locks.pop(conn, None)
            conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


_BROKERS: Dict[int, MqttBroker] = {}
_BROKERS_LOCK = make_lock("leaf")


def get_mqtt_broker(port: int = 0, host: str = "127.0.0.1") -> MqttBroker:
    with _BROKERS_LOCK:
        if port and port in _BROKERS:
            return _BROKERS[port]
        b = MqttBroker(host, port)
        _BROKERS[b.port] = b
        return b


# -- elements ----------------------------------------------------------------

@register_element
class MqttSink(Element):
    """``mqttsink``: publish the stream to an MQTT topic with the
    reference's 1024-B header (mqttsink.c role)."""

    FACTORY = "mqttsink"
    PROPERTIES = {
        "host": ("127.0.0.1", "broker host"),
        "port": (1883, "broker port"),
        "pub-topic": ("nnstreamer", "topic to publish"),
        "ntp-host": (None, "NTP server(s) for epoch alignment, comma-sep"),
        "keepalive": (30, "MQTT keepalive seconds declared in CONNECT "
                          "and honored by a PINGREQ pinger (0 = off)"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def start(self):
        from ..utils.ntp import stream_origin_epoch_us

        self._client = MqttClient(str(self.host), int(self.port),
                                  f"nns-sink-{self.name}",
                                  keepalive=int(self.keepalive),
                                  publish_only=True)
        self._base_epoch_us = stream_origin_epoch_us(self.ntp_host,
                                                     self.name)
        self._caps_str = ""

    def stop(self):
        self._client.close()

    def set_caps(self, pad, caps):
        self._caps_str = str(caps)

    def chain(self, pad, buf):
        from ..pipeline.tracing import record_copy

        mems = [np.ascontiguousarray(buf.np(i)).tobytes()
                for i in range(buf.num_tensors)]
        from ..obs.clock import wall_us

        hdr = pack_header([len(m) for m in mems], self._base_epoch_us,
                          wall_us(), buf.duration, None,
                          buf.pts, self._caps_str,
                          ctx=buf.extra.get("nns_trace"))
        record_copy(len(hdr) + sum(len(m) for m in mems))
        self._client.publish(str(self.pub_topic), hdr + b"".join(mems))
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self.post_eos_reached()


@register_element
class MqttSrc(Source):
    """``mqttsrc``: subscribe to an MQTT topic, reconstruct buffers from
    the 1024-B header (mqttsrc.c role); ``sync-pts`` re-bases sender PTS
    via the embedded base-time epoch."""

    FACTORY = "mqttsrc"
    PROPERTIES = {
        "host": ("127.0.0.1", "broker host"),
        "port": (1883, "broker port"),
        "sub-topic": ("nnstreamer", "topic to subscribe"),
        "caps": (None, "override out caps (else the header's caps string)"),
        "num-buffers": (-1, "stop after N buffers, -1 unlimited"),
        "sync-pts": (False, "re-base incoming PTS onto this host's clock"),
        "ntp-host": (None, "NTP server(s) for epoch alignment, comma-sep"),
        # reference mqttsrc launch-line parity (ssat sets both): debug
        # toggles its verbose logging, is-live marks the live-source
        # flag — this source is always live, the flags are accepted
        # state
        "debug": (False, "reference mqttsrc debug flag"),
        "is-live": (True, "reference live-source flag (always live "
                          "here)"),
        "keepalive": (30, "MQTT keepalive seconds declared in CONNECT "
                          "and honored by a PINGREQ pinger (0 = off)"),
    }

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        from ..utils.ntp import stream_origin_epoch_us

        self._base_epoch_us = stream_origin_epoch_us(self.ntp_host,
                                                     self.name)
        self._client = MqttClient(str(self.host), int(self.port),
                                  f"nns-src-{self.name}",
                                  keepalive=int(self.keepalive))
        self._client.subscribe(str(self.sub_topic))
        # paced by the broker's TCP stream and drained every create()
        # (QoS-0 pub/sub transport; query-path overload is handled by
        # admission control in query/overload.py)
        # nnslint: allow(unbounded-queue)
        self._fifo: _queue.Queue = _queue.Queue()
        self._count = 0
        self._first = None
        threading.Thread(target=self._pump, daemon=True,
                         name=f"mqttsrc:{self.name}").start()

    def stop(self):
        self._client.close()
        super()._halt()

    def _pump(self) -> None:
        while True:
            got = self._client.recv_publish()
            if got is None:
                self._fifo.put(None)
                return
            _, payload = got
            try:
                self._fifo.put(self._parse(payload))
            except Exception as e:  # noqa: BLE001 - malformed frame
                logger.warning("%s: dropping malformed frame: %r",
                               self.name, e)

    def _parse(self, payload: bytes):
        sizes, base_us, _sent, duration, _dts, pts, caps_str = \
            unpack_header(payload)
        ctx = header_trace_ctx(payload)
        body = payload[HDR_LEN:]
        if sum(sizes) > len(body):
            raise ValueError(f"truncated frame: header declares "
                             f"{sum(sizes)}B, body has {len(body)}B")
        mems, off = [], 0
        for s in sizes:
            mems.append(body[off:off + s])
            off += s
        if self.sync_pts and pts is not None:
            pts = pts + (base_us - self._base_epoch_us) * 1000
        return mems, duration, pts, caps_str, ctx

    def _next(self):
        while not self._halted.is_set():
            try:
                return self._fifo.get(timeout=0.1)
            except _queue.Empty:
                continue
        return None

    def negotiate(self) -> Caps:
        if self.caps:
            c = self.caps
            self._caps = Caps.from_string(c) if isinstance(c, str) else c
        else:
            item = self._next()
            if item is None:
                raise ValueError(f"{self.name}: no frame before teardown; "
                                 "set the caps property")
            self._first = item
            self._caps = Caps.from_string(item[3])
        self._config = config_from_caps(self._caps)
        return caps_from_config(self._config)

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.num_buffers)
        if n >= 0 and self._count >= n:
            return None
        if self._first is not None:
            item, self._first = self._first, None
        else:
            item = self._next()
        while item is not None:
            mems, duration, pts, _caps, ctx = item
            infos = self._config.info
            try:
                if len(mems) != infos.num_tensors:
                    raise ValueError(
                        f"frame has {len(mems)} memories, negotiated "
                        f"{infos.num_tensors}")
                tensors = [np.frombuffer(mem, info.np_dtype)
                           .reshape(info.np_shape)
                           for mem, info in zip(mems, infos)]
            except ValueError as e:
                # a foreign publisher on the topic; drop, keep streaming
                logger.warning("%s: dropping mismatched frame: %s",
                               self.name, e)
                item = self._next()
                continue
            self._count += 1
            out = TensorBuffer(tensors=tensors, pts=pts,
                               duration=duration)
            if ctx is not None:
                out.extra["nns_trace"] = ctx
            return out
        return None
