"""Distributed query layer (L5): offload tensor streams between hosts."""

from .client import QueryConnection, TensorQueryClient
from .protocol import (Message, decode_tensors, encode_tensors, recv_msg,
                       send_msg)
from .server import (QueryServer, TensorQueryServerSink, TensorQueryServerSrc,
                     get_server, shutdown_server)

__all__ = [
    "QueryConnection", "TensorQueryClient", "QueryServer",
    "TensorQueryServerSrc", "TensorQueryServerSink", "get_server",
    "shutdown_server", "Message", "encode_tensors", "decode_tensors",
    "send_msg", "recv_msg",
]
