"""Distributed query layer (L5): offload tensor streams between hosts."""

from .client import (FailoverConnection, QueryConnection, TensorQueryClient,
                     parse_endpoints)
from .overload import (AdmissionController, ShedError, ShedPolicy,
                       TokenBucket, WatermarkShedPolicy, qos_of_class)
from .protocol import (Message, decode_tensors, encode_tensors, recv_msg,
                       send_msg)
from .resilience import (STATS, CircuitBreaker, CircuitOpenError,
                         HealthMonitor, RetryExhausted, RetryPolicy)
from .server import (QueryServer, TensorQueryServerSink, TensorQueryServerSrc,
                     get_server, shutdown_server)

__all__ = [
    "QueryConnection", "FailoverConnection", "TensorQueryClient",
    "parse_endpoints", "QueryServer",
    "TensorQueryServerSrc", "TensorQueryServerSink", "get_server",
    "shutdown_server", "Message", "encode_tensors", "decode_tensors",
    "send_msg", "recv_msg",
    "STATS", "RetryPolicy", "RetryExhausted", "CircuitBreaker",
    "CircuitOpenError", "HealthMonitor",
    "ShedError", "ShedPolicy", "WatermarkShedPolicy",
    "AdmissionController", "TokenBucket", "qos_of_class",
]
