"""Tensor query client: offload frames to a remote serving pipeline.

Parity with gst/nnstreamer/tensor_query/tensor_query_client.c: chain sends
the frame over the transport, blocks on an async queue for the answer
(:656-743), with reconnect/retry (:368-380,728-732) and a caps handshake
over the same channel (:512-559).
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
import time
from typing import Optional

from ..pipeline.caps import Caps
from ..pipeline.element import Element, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import tensors_template_caps
from .protocol import (Message, T_BYE, T_DATA, T_HELLO, T_REPLY,
                       decode_tensors, encode_tensors, recv_msg, send_msg)


class QueryConnection:
    """Socket + reader thread + reply queue, with reconnect."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 max_retries: int = 3):
        self.host, self.port = host, port
        self.timeout = timeout
        self.max_retries = max_retries
        self.replies: _queue.Queue = _queue.Queue()
        self.server_caps: Optional[str] = None
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._seq = 0

    def connect(self) -> None:
        last_err: Optional[Exception] = None
        for attempt in range(self.max_retries):
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
                sock.settimeout(None)
                self._sock = sock
                self._stop.clear()
                self._reader = threading.Thread(
                    target=self._read_loop, daemon=True, name="query-reader")
                self._reader.start()
                # caps handshake
                send_msg(sock, Message(T_HELLO))
                return
            except OSError as exc:
                last_err = exc
                time.sleep(0.2 * (attempt + 1))
        raise ConnectionError(
            f"cannot connect to {self.host}:{self.port}: {last_err}")

    def _read_loop(self) -> None:
        sock = self._sock
        while not self._stop.is_set():
            try:
                msg = recv_msg(sock)
            except ValueError as e:   # bad magic / CRC: stream corrupt
                from ..utils.log import logger

                logger.error("query client: corrupt stream: %s", e)
                msg = None
            if msg is None:
                self.replies.put(None)  # signal disconnect
                return
            if msg.type == T_HELLO:
                self.server_caps = msg.payload.decode()
            elif msg.type == T_REPLY:
                self.replies.put(msg)

    def query(self, buf: TensorBuffer) -> Optional[TensorBuffer]:
        """Send one frame, await ITS reply (matched by seq; stale replies
        from timed-out requests are discarded), reconnecting once."""
        self._seq += 1
        seq = self._seq
        msg = Message(T_DATA, seq=seq, pts=buf.pts or 0,
                      payload=encode_tensors(buf))
        for attempt in (0, 1):
            try:
                send_msg(self._sock, msg)
            except (OSError, AttributeError):
                if attempt:
                    raise
                self._reconnect()
                continue
            reply = self._await_reply(seq)
            if reply is None:  # disconnected mid-wait → retry once
                if attempt:
                    raise ConnectionError("server closed connection")
                self._reconnect()
                continue
            out = buf.with_tensors(decode_tensors(reply.payload))
            out.pts = reply.pts
            return out
        return None

    def _await_reply(self, seq: int) -> Optional[Message]:
        import time as _time

        deadline = _time.monotonic() + self.timeout
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"no reply within {self.timeout}s")
            try:
                reply = self.replies.get(timeout=remaining)
            except _queue.Empty:
                raise TimeoutError(
                    f"no reply within {self.timeout}s") from None
            if reply is None or reply.seq == seq:
                return reply
            # stale reply from an earlier timed-out request: discard

    def _reconnect(self) -> None:
        self.close(send_bye=False)
        # drop anything queued by the dying reader (incl. its None sentinel)
        while True:
            try:
                self.replies.get_nowait()
            except _queue.Empty:
                break
        self.connect()

    def close(self, send_bye: bool = True) -> None:
        self._stop.set()
        sock = self._sock
        if sock is not None:
            if send_bye:
                try:
                    send_msg(sock, Message(T_BYE))
                except OSError:
                    pass
            try:
                sock.close()
            except OSError:
                pass
        self._sock = None


@register_element
class TensorQueryClient(Element):
    FACTORY = "tensor_query_client"
    PROPERTIES = {
        "host": ("127.0.0.1", "server host (reference: the client's "
                              "own bind address; kept as the server "
                              "fallback when dest-* is unset)"),
        "port": (0, "server port (fallback when dest-port unset)"),
        "dest-host": (None, "server host (TCP) or MQTT broker host "
                            "(HYBRID) — the reference's addressing: "
                            "every ssat line uses dest-host/dest-port"),
        "dest-port": (None, "server/broker port"),
        "connect-type": ("tcp", "TCP | HYBRID (reference nicks; hybrid "
                                "discovers the data address from the "
                                "retained MQTT record for the topic)"),
        "topic": (None, "hybrid: discovery topic"),
        "timeout": (10.0, "reply timeout seconds"),
        "max-retries": (3, "connect retries"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")
        self.add_src_pad(tensors_template_caps(), "src")

    def _server_address(self) -> "tuple[str, int]":
        """Resolve the data-channel address the reference way: HYBRID
        looks up the retained record for the topic on the MQTT broker
        at dest-host:dest-port (tensor_query_client.c via
        nnstreamer-edge); TCP takes dest-host:dest-port directly, with
        the legacy host/port pair as fallback."""
        if str(self.connect_type).lower() == "hybrid":
            from .mqtt import fetch_retained_record

            if self.topic in (None, ""):
                raise ValueError(f"{self.name}: connect-type=HYBRID "
                                 "requires topic")
            broker_host = str(self.dest_host or "127.0.0.1")
            broker_port = int(self.dest_port or 1883)
            record = fetch_retained_record(
                broker_host, broker_port, f"nns/query/{self.topic}",
                float(self.timeout), f"nns-query-cli-{self.name}")
            if not record:
                raise ConnectionError(
                    f"{self.name}: no retained discovery record for "
                    f"topic {self.topic!r} on "
                    f"{broker_host}:{broker_port}")
            host, sep, port = record.decode().rpartition(":")
            if not sep or not port.isdigit():
                raise ConnectionError(
                    f"{self.name}: malformed discovery record "
                    f"{record!r} (want host:port)")
            return host, int(port)
        if self.dest_port not in (None, "", 0):
            return str(self.dest_host or "127.0.0.1"), int(self.dest_port)
        if self.dest_host not in (None, ""):
            # silently connecting to the legacy host/port when only
            # dest-host was given would hit the wrong machine
            raise ValueError(f"{self.name}: dest-host={self.dest_host!r} "
                             "needs dest-port")
        return str(self.host), int(self.port)

    def start(self):
        host, port = self._server_address()
        self.conn = QueryConnection(host, port,
                                    float(self.timeout),
                                    int(self.max_retries))
        self.conn.connect()

    def stop(self):
        conn = getattr(self, "conn", None)
        if conn is not None:
            conn.close()

    def set_caps(self, pad, caps):
        # announce the server's answer caps when it advertised them,
        # else assume passthrough shape
        sc = self.conn.server_caps
        if sc:
            self.announce_src_caps(Caps.from_string(sc))
        else:
            super().set_caps(pad, caps)

    def chain(self, pad, buf):
        out = self.conn.query(buf)
        if out is None:
            return FlowReturn.ERROR
        return self.push(out)
