"""Tensor query client: offload frames to a remote serving pipeline.

Parity with gst/nnstreamer/tensor_query/tensor_query_client.c: chain sends
the frame over the transport, blocks on an async queue for the answer
(:656-743), with reconnect/retry (:368-380,728-732) and a caps handshake
over the same channel (:512-559).

Resilience (query/resilience.py): connects back off exponentially with
jitter (:class:`RetryPolicy`); each endpoint sits behind a
:class:`CircuitBreaker` so a dead server fails fast instead of eating a
timeout per frame; a :class:`HealthMonitor` heartbeats the active
endpoint over ``T_PING``/``T_PONG`` and a dead verdict triggers failover
to the next entry of the ``dest-hosts`` list.  The ``fallback`` property
picks what a frame does when every endpoint is down: ``error`` (pipeline
error, the reference default), ``passthrough`` (push the input frame
unchanged — graceful degradation), or ``drop``.

Overload (query/overload.py): the connection declares a QoS class
(``qos`` property, or inherited from the first frame's ``nns_class``
tag) in the T_HELLO handshake; a ``T_SHED`` answer from the server's
admission control surfaces as :class:`ShedError` and maps into the
same fallback machinery — but breakers record SUCCESS on a shed (the
server is alive and protecting itself); with alternates the client
rotates to the next endpoint immediately (routing away is what an
overloaded or draining server asked for), alone it floors its retry
backoff at the server's retry-after hint capped by the request budget.
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.sanitizer import make_lock, make_rlock
from ..obs.clock import OffsetEstimator, wall_us
from ..obs.span import TraceContext
from ..pipeline.caps import Caps
from ..pipeline.element import Element, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer, default_pool
from ..tensor.caps_util import tensors_template_caps
from .overload import ShedError, qos_of_class
from .protocol import (Message, T_BYE, T_DATA, T_HELLO, T_PING, T_PONG,
                       T_REPLY, T_SHED, T_TRACE, decode_tensors,
                       parse_retry_after, recv_msg, send_msg,
                       send_tensors, shutdown_close)
from .protocol import create_connection as checked_connect
from .resilience import (STATS, CircuitBreaker, CircuitOpenError,
                         HealthMonitor, RetryExhausted, RetryPolicy)


class _PongWaiter:
    """One outstanding ping: completion event + the pong's wall-clock
    stamp (0 = peer predates the stamp)."""

    __slots__ = ("evt", "epoch_us")

    def __init__(self) -> None:
        self.evt = threading.Event()
        self.epoch_us = 0


class QueryConnection:
    """Socket + reader thread + reply queue, with reconnect.

    One TCP connection to one endpoint.  ``query()`` owns the whole
    request budget (``timeout`` seconds covering send, reconnect, and
    reply wait); ``ping()`` is the heartbeat probe matched by seq on the
    same stream, handled out of band by the reader thread.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 max_retries: int = 3,
                 retry: Optional[RetryPolicy] = None,
                 qos: Optional[str] = None,
                 model: Optional[str] = None):
        self.host, self.port = host, port
        self.timeout = timeout
        self.max_retries = max_retries
        #: QoS class declared to the server in the T_HELLO handshake
        #: (query/overload.py admission control: bronze sheds first,
        #: gold last).  None = unnegotiated; the first query whose
        #: ``buf.extra["nns_class"]`` implies a class negotiates it
        #: late (the loadgen's class tagging becomes the QoS default)
        self.qos = qos
        #: model identity declared in the handshake (a ``model=`` HELLO
        #: token): the fleet router's consistent-hash key — connections
        #: naming the same model concentrate on the same workers so
        #: their cross-stream buckets stay dense (fleet/router.py).
        #: Plain servers ignore it.
        self.model = model
        self.retry = retry or RetryPolicy(max_attempts=max(1, max_retries),
                                          base_delay=0.05, max_delay=0.5)
        # bounded by the request protocol: at most one outstanding
        # reply (plus a disconnect sentinel) per in-flight query
        # nnslint: allow(unbounded-queue)
        self.replies: _queue.Queue = _queue.Queue()
        self.server_caps: Optional[str] = None
        #: set when the server's HELLO answer lands (the caps arrive on
        #: the reader thread; waiters — the fleet router forwarding a
        #: handshake — block on this instead of polling)
        self._caps_evt = threading.Event()
        self._pool = default_pool()   # reply payloads land in recycled slabs
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._seq = 0
        self._send_lock = make_lock("query.send")  # query+ping share the
        #                                            stream
        self._pong_waiters: Dict[int, "_PongWaiter"] = {}
        self._waiters_lock = make_lock("query.registry")
        self._offset_sampled = float("-inf")   # last ping-sample time
        #: server clock offset (NTP-midpoint over reply epoch stamps)
        self.offset = OffsetEstimator()
        #: T_TRACE span-batch payloads from the server, drained by the
        #: client element into its pipeline tracer (bounded: a client
        #: with no tracer silently ages them out)
        import collections

        self._trace_in: "collections.deque" = collections.deque(maxlen=256)
        #: loadgen hook (slo/loadgen.py): called as ``(request_class,
        #: latency_s, ok)`` after every query() — service latency from
        #: send to reply, per-class via ``buf.extra["nns_class"]``.
        #: Fires on raising paths too (timeouts, dead endpoints) but
        #: NOT on sheds: a T_SHED's near-instant round trip would
        #: flatter the admitted-traffic service distribution.
        #: None (the default) costs one attribute test per query.
        self.on_outcome: Optional[Callable[[str, float, bool], None]] = None

    def _hello_payload(self) -> bytes:
        """`;`-token handshake payload (protocol.parse_hello_tokens):
        QoS class for admission control, model identity for fleet
        routing — both optional, empty when neither is set."""
        parts = []
        if self.qos:
            parts.append(f"qos={self.qos}")
        if self.model:
            parts.append(f"model={self.model}")
        return ";".join(parts).encode()

    def connect(self) -> None:
        def _dial():
            sock = checked_connect(
                (self.host, self.port), timeout=self.timeout)
            sock.settimeout(None)
            self._sock = sock
            self._stop.clear()
            reader = threading.Thread(
                target=self._read_loop, daemon=True, name="query-reader")
            self._reader = reader
            reader.start()
            try:
                # caps handshake; declares this connection's QoS class
                # / model identity when set (server-side admission
                # control; fleet-router placement)
                self._send(Message(T_HELLO,
                                   payload=self._hello_payload()))
            except OSError:
                # tear this half-made connection down before the retry:
                # otherwise every failed attempt leaks a socket and a
                # reader thread whose None sentinel would later be
                # mistaken for a disconnect on the healthy link
                shutdown_close(sock)
                self._sock = None
                reader.join(timeout=5)
                while True:
                    try:
                        self.replies.get_nowait()
                    except _queue.Empty:
                        break
                raise

        try:
            self.retry.run(_dial, retry_on=(OSError,),
                           counter="query.connect")
        except RetryExhausted as exc:
            raise ConnectionError(
                f"cannot connect to {self.host}:{self.port}: "
                f"{exc.__cause__}") from exc.__cause__

    def _send(self, msg: Message) -> None:
        # serialize writers: a heartbeat ping must never interleave with
        # a partially-written DATA frame from the streaming thread
        with self._send_lock:
            send_msg(self._sock, msg)

    def _read_loop(self) -> None:
        sock = self._sock
        while not self._stop.is_set():
            try:
                msg = recv_msg(sock, pool=self._pool)
            except ValueError as e:   # bad magic / CRC: stream corrupt
                from ..utils.log import logger

                logger.error("query client: corrupt stream: %s", e)
                msg = None
            if msg is None:
                self.replies.put(None)  # signal disconnect
                return
            if msg.type == T_HELLO:
                self.server_caps = msg.payload.decode()
                self._caps_evt.set()
            elif msg.type in (T_REPLY, T_SHED):
                # a shed is a first-class answer: it rides the reply
                # queue so _await_reply matches it to ITS request by seq
                self.replies.put(msg)
            elif msg.type == T_TRACE:
                # server timeline piggyback: park the raw JSON batch;
                # the element thread parses and merges it (or it ages
                # out of the bounded deque when no tracer wants it)
                self._trace_in.append(bytes(msg.payload))
            elif msg.type == T_PONG:
                with self._waiters_lock:
                    waiter = self._pong_waiters.pop(msg.seq, None)
                if waiter is not None:
                    waiter.epoch_us = msg.epoch_us
                    waiter.evt.set()

    def wait_server_caps(self, timeout: float = 2.0) -> Optional[str]:
        """Block until the server's HELLO answer (its caps string)
        arrived, or ``timeout`` — the handshake-forwarding path's read
        (a router must answer the client's HELLO with the WORKER's
        caps, which land asynchronously on the reader thread)."""
        self._caps_evt.wait(timeout)
        return self.server_caps

    def ping(self, timeout: float = 1.0) -> float:
        """Heartbeat probe: send ``T_PING``, await the matching
        ``T_PONG``.  Returns the RTT in seconds; raises ``TimeoutError``
        / ``OSError`` on a dead or silent peer.

        A pong's wall-clock stamp feeds the clock-offset estimator:
        ping service time is near zero, so these are the samples that
        bound the offset error by rtt/2 (a REPLY stamp rides on top of
        model latency — its bias equals half the service time, which
        min-RTT filtering then discards once a ping sample exists)."""
        waiter = _PongWaiter()
        with self._waiters_lock:
            # seq allocation must be atomic with waiter registration:
            # the monitor probe thread, the element thread's offset
            # sampler and query() all share this counter — a lost
            # update would give two pings one seq and strand a waiter
            self._seq += 1
            seq = self._seq
            self._pong_waiters[seq] = waiter
        try:
            t0 = time.monotonic()
            t_send_us = wall_us()
            try:
                self._send(Message(T_PING, seq=seq))
            except AttributeError:   # _sock is None: closed under us
                raise ConnectionError("not connected") from None
            if not waiter.evt.wait(timeout):
                raise TimeoutError(
                    f"no pong from {self.host}:{self.port} "
                    f"within {timeout}s")
            if waiter.epoch_us:
                self.offset.add_sample(t_send_us, wall_us(),
                                       waiter.epoch_us)
            return time.monotonic() - t0
        finally:
            with self._waiters_lock:
                self._pong_waiters.pop(seq, None)

    def sample_clock_offset(self, max_age_s: float = 2.0,
                            timeout: float = 1.0) -> None:
        """Refresh the offset estimate with a ping sample unless a
        recent one exists.  The ping runs on a short-lived daemon
        thread: the caller is the STREAMING thread mid-chain, and a
        degraded peer must cost it nothing (failures are ignored — the
        reply-stamp fallback samples keep the estimator populated)."""
        now = time.monotonic()
        if now - self._offset_sampled < max_age_s:
            return
        self._offset_sampled = now

        def _probe():
            try:
                self.ping(timeout=timeout)
            except (TimeoutError, ConnectionError, OSError):
                pass

        threading.Thread(target=_probe, daemon=True,
                         name="query-offset-probe").start()

    def query(self, buf: TensorBuffer) -> Optional[TensorBuffer]:
        """Send one frame, await ITS reply (matched by seq; stale replies
        from timed-out requests are discarded), reconnecting within the
        request's deadline budget (``timeout`` covers send + reconnect +
        reply).

        When :attr:`on_outcome` is set (the loadgen hook), the request's
        class tag (``buf.extra["nns_class"]``, default ``"default"``),
        service latency and success flag are reported after every
        attempt — including raising ones, so error accounting sees
        timeouts and dead endpoints, not just clean replies."""
        hook = self.on_outcome
        if hook is None:
            return self._query(buf)
        cls = str(buf.extra.get("nns_class", "default"))
        t0 = time.monotonic()
        try:
            out = self._query(buf)
        except ShedError:
            # a shed is not a service outcome: its ~instant round trip
            # in the service histogram would flatter the admitted
            # traffic's latency — the caller's shed accounting owns it
            raise
        except BaseException:
            hook(cls, time.monotonic() - t0, False)
            raise
        hook(cls, time.monotonic() - t0, True)
        return out

    def _negotiate_qos_late(self, buf: TensorBuffer) -> None:
        """Default the connection's QoS class from the request's class
        tag: the first ``buf.extra["nns_class"]`` that implies a QoS
        class re-announces the handshake with it (servers accept a
        fresh T_HELLO at any time), so loadgen-tagged traffic gets
        tiered shedding without explicit configuration."""
        implied = qos_of_class(buf.extra.get("nns_class"))
        if implied is None:
            return
        self.qos = implied
        try:
            self._send(Message(T_HELLO, payload=self._hello_payload()))
        except (OSError, AttributeError):
            pass   # connection is down: connect() re-announces

    def _query(self, buf: TensorBuffer) -> Optional[TensorBuffer]:
        if self.qos is None:
            self._negotiate_qos_late(buf)
        with self._waiters_lock:   # shared with ping allocations
            self._seq += 1
            seq = self._seq
        deadline = time.monotonic() + self.timeout
        ctx = buf.extra.get("nns_trace") or TraceContext()
        for attempt in (0, 1):
            t_send_us = wall_us()
            try:
                # scatter-gather framing: tensor payloads go to the
                # kernel as views, no per-frame blob materialization
                with self._send_lock:
                    send_tensors(self._sock, T_DATA, buf, seq=seq,
                                 pts=buf.pts or 0,
                                 trace_id=ctx.trace_id,
                                 span_id=ctx.span_id,
                                 origin_us=ctx.origin_us)
            except (OSError, AttributeError):
                if attempt:
                    raise
                STATS.incr("query.reconnects")
                self._reconnect(deadline)
                continue
            reply = self._await_reply(seq, deadline)
            if reply is None:  # disconnected mid-wait → retry once
                if attempt:
                    raise ConnectionError("server closed connection")
                STATS.incr("query.reconnects")
                self._reconnect(deadline)
                continue
            if reply.type == T_SHED:
                # explicit load shed: the server refused this request
                # by admission control and told us when to come back.
                # NOT a failure — the caller's resilience layer must
                # keep breakers closed and honor the retry-after.
                retry_after = parse_retry_after(reply.payload)
                qos = self.qos or "default"
                STATS.incr("query.sheds")
                STATS.incr(f"query.sheds.{qos}")
                raise ShedError(retry_after, qos=qos)
            if reply.epoch_us:
                # reply stamps carry the server wall clock: one offset
                # sample per round trip, min-RTT filtered (obs/clock.py)
                self.offset.add_sample(t_send_us, wall_us(),
                                       reply.epoch_us)
            out = buf.with_tensors(decode_tensors(reply.payload))
            out.pts = reply.pts
            out.lease = reply.lease   # views alias the pooled slab
            return out
        return None

    def drain_traces(self) -> List[bytes]:
        """Pending T_TRACE span batches (raw JSON), oldest first."""
        out: List[bytes] = []
        while True:
            try:
                out.append(self._trace_in.popleft())
            except IndexError:
                return out

    def _await_reply(self, seq: int,
                     deadline: Optional[float] = None) -> Optional[Message]:
        if deadline is None:
            deadline = time.monotonic() + self.timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"no reply within {self.timeout}s")
            try:
                reply = self.replies.get(timeout=remaining)
            except _queue.Empty:
                raise TimeoutError(
                    f"no reply within {self.timeout}s") from None
            if reply is None or reply.seq == seq:
                return reply
            # stale reply from an earlier timed-out request: discard
            STATS.incr("query.stale_replies")

    def _reconnect(self, deadline: Optional[float] = None) -> None:
        self.close(send_bye=False)
        # drop anything queued by the dying reader (incl. its None sentinel)
        while True:
            try:
                self.replies.get_nowait()
            except _queue.Empty:
                break
        if deadline is not None:
            # bound the reconnect by the request's remaining budget
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise TimeoutError(
                    f"no budget left to reconnect to "
                    f"{self.host}:{self.port}")
            retry, self.retry = self.retry, self.retry.with_deadline(budget)
            try:
                self.connect()
            finally:
                self.retry = retry
        else:
            self.connect()

    def close(self, send_bye: bool = True) -> None:
        self._stop.set()
        sock = self._sock
        if sock is not None:
            if send_bye:
                try:
                    # send on the CAPTURED sock (still under the send
                    # lock): _send re-reads self._sock, which a racing
                    # _reconnect may have nulled — an AttributeError
                    # here would escape teardown
                    with self._send_lock:
                        send_msg(sock, Message(T_BYE))
                except OSError:
                    pass
            # shutdown-then-close wakes the reader thread blocked in
            # recv (a plain close would leave it blocked forever and the
            # server would never see a FIN — protocol.py)
            shutdown_close(sock)
        self._sock = None


class FailoverConnection:
    """Multi-endpoint query connection: one active
    :class:`QueryConnection` at a time, per-endpoint circuit breakers,
    optional heartbeat-driven failover.

    ``endpoints`` is an ordered ``[(host, port), …]`` preference list
    (the ``dest-hosts`` property).  A query failure records on the active
    endpoint's breaker and rotates to the next endpoint whose breaker
    admits a call; a heartbeat ``dead`` verdict demotes the active
    endpoint between frames so the next query fails over without eating
    a full reply timeout first.

    The endpoint list is HOT-updatable (:meth:`set_endpoints` — the
    fleet router's rebalance path): the active connection survives the
    update when its endpoint is still listed, so a membership change
    never causes a reconnect storm; a removed active endpoint rotates
    on the NEXT query.

    ``shed_passthrough=True`` (the router's forwarding mode) raises a
    lone endpoint's :class:`ShedError` immediately instead of honoring
    its retry-after in place — a proxy sleeping out the hint would turn
    an explicit fast shed into opaque latency inside the caller's own
    budget.  With alternates, sheds still rotate (routing away IS
    honoring the hint) and only an all-candidates shed propagates.
    """

    _FAILURE = (TimeoutError, ConnectionError, OSError, AttributeError)

    def __init__(self, endpoints: List[Tuple[str, int]],
                 timeout: float = 10.0, max_retries: int = 3,
                 retry: Optional[RetryPolicy] = None,
                 breaker_failures: int = 5,
                 breaker_cooldown: float = 30.0,
                 heartbeat_interval: float = 0.0,
                 heartbeat_max_missed: int = 3,
                 name: str = "query",
                 qos: Optional[str] = None,
                 model: Optional[str] = None,
                 shed_passthrough: bool = False):
        if not endpoints:
            raise ValueError("FailoverConnection needs >= 1 endpoint")
        self.endpoints = list(endpoints)
        self.timeout = timeout
        self.max_retries = max_retries
        self.qos = qos
        self.model = model
        self.name = name
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown = float(breaker_cooldown)
        self._shed_passthrough = bool(shed_passthrough)
        self.retry = retry or RetryPolicy(max_attempts=max(1, max_retries),
                                          base_delay=0.05, max_delay=0.5)
        self.breakers = [self._make_breaker(h, p)
                         for h, p in self.endpoints]
        self._idx = 0                    # preferred endpoint index
        self._active: Optional[QueryConnection] = None
        self._active_idx: Optional[int] = None
        self._active_key: Optional[str] = None   # lock-free monitor read
        self._dead = threading.Event()   # heartbeat verdict on active
        self._lock = make_rlock("query.client")
        self.monitor: Optional[HealthMonitor] = None
        if heartbeat_interval > 0:
            self.monitor = HealthMonitor(
                interval=heartbeat_interval,
                max_missed=heartbeat_max_missed,
                on_down=self._on_endpoint_down, name=name)

    # -- endpoint bookkeeping ------------------------------------------------
    def _make_breaker(self, host: str, port: int) -> CircuitBreaker:
        return CircuitBreaker(failure_threshold=self.breaker_failures,
                              cooldown=self.breaker_cooldown,
                              name=f"{self.name}:{host}:{port}")

    def _key(self, idx: int) -> str:
        h, p = self.endpoints[idx]
        return f"{h}:{p}"

    def set_endpoints(self, endpoints: List[Tuple[str, int]]) -> None:
        """Hot ``dest-hosts`` update (the fleet router's rebalance
        primitive).  Endpoints present before AND after keep their
        circuit-breaker state; new ones start fresh.  When the ACTIVE
        endpoint survives the update, the live connection is kept
        untouched — a fleet membership change must move only the
        clients whose assignment changed, never storm every socket.
        When it was removed, the connection closes and the next query
        dials the new preferred endpoint (rotate-on-update)."""
        endpoints = [(str(h), int(p)) for h, p in endpoints]
        if not endpoints:
            raise ValueError("set_endpoints needs >= 1 endpoint")
        with self._lock:
            kept = {self._key(i): self.breakers[i]
                    for i in range(len(self.endpoints))}
            active_key = self._active_key
            self.endpoints = endpoints
            self.breakers = [kept.get(f"{h}:{p}")
                             or self._make_breaker(h, p)
                             for h, p in endpoints]
            keys = [self._key(i) for i in range(len(endpoints))]
            if active_key is not None and active_key in keys:
                # active endpoint survives: same socket, new index
                self._active_idx = self._idx = keys.index(active_key)
                return
            # active endpoint removed (or none yet): next query starts
            # at the new preference head.  Close WITHOUT a failure mark
            # — this is a routing decision, not an endpoint fault — and
            # WITHOUT a BYE: a goodbye send can block on a wedged
            # peer's full socket buffer, and the router calls this
            # under its membership lock for every displaced client
            # (one sick worker must not stall the whole control
            # plane); shutdown_close's FIN tells the worker enough.
            if self._active is not None:
                if self.monitor is not None and active_key is not None:
                    self.monitor.unwatch(active_key)
                self._active.close(send_bye=False)
                self._active = None
                STATS.incr("query.rebalances")
            self._active_idx = None
            self._active_key = None
            self._idx = 0
            self._dead.clear()

    def set_qos(self, qos: Optional[str]) -> None:
        """Update the QoS class mid-stream: the active connection
        re-announces the full token payload (servers accept a fresh
        T_HELLO at any time) and later dials inherit it."""
        with self._lock:
            self.qos = qos
            conn = self._active
        if conn is not None:
            conn.qos = qos
            try:
                conn._send(Message(T_HELLO,
                                   payload=conn._hello_payload()))
            except (OSError, AttributeError):
                pass   # next dial re-announces

    def _on_endpoint_down(self, key: str) -> None:
        """Heartbeat verdict: the active endpoint stopped answering.
        Mark it so the next query fails over immediately instead of
        waiting out a reply timeout on a dead socket."""
        # deliberately lock-free: the query thread holds self._lock for
        # the whole (possibly seconds-long, backoff-sleeping) dial in
        # _ensure_active, and heartbeats for other endpoints must not
        # stall behind it.  A stale match only sets a flag the next
        # _ensure_active clears after reconnecting.
        if key == self._active_key:
            self._dead.set()

    @property
    def server_caps(self) -> Optional[str]:
        with self._lock:
            return (self._active.server_caps
                    if self._active is not None else None)

    def wait_server_caps(self, timeout: float = 2.0) -> Optional[str]:
        """Active connection's :meth:`QueryConnection.wait_server_caps`
        (None when no endpoint is live)."""
        with self._lock:
            conn = self._active
        return (conn.wait_server_caps(timeout)
                if conn is not None else None)

    @property
    def active_endpoint(self) -> Optional[Tuple[str, int]]:
        with self._lock:
            return (self.endpoints[self._active_idx]
                    if self._active_idx is not None else None)

    def health_report(self) -> Dict[str, Dict[str, object]]:
        return self.monitor.report() if self.monitor is not None else {}

    def degraded(self) -> bool:
        """True while this connection runs in a reduced mode: no live
        endpoint (degraded start / mid-stream loss awaiting the next
        frame's redial) or any endpoint breaker OPEN.  Scrape-time read
        for the /healthz readiness state — deliberately lock-free
        (a torn read costs one conservative scrape, not a stall behind
        a seconds-long dial holding self._lock)."""
        if self._active is None:
            return True
        return any(b.state == "open" for b in self.breakers)

    def sample_clock_offset(self) -> None:
        """Rate-limited ping-based offset refresh on the active
        connection (traced clients call this per frame; it no-ops
        within the sample window)."""
        with self._lock:
            conn = self._active
        if conn is not None:
            conn.sample_clock_offset()

    def drain_remote_traces(self) -> List[Tuple[bytes, int, str]]:
        """Pending server span batches from the active connection:
        ``(raw_json, offset_us, endpoint_key)`` triples, offset already
        min-RTT-filtered per connection."""
        with self._lock:
            conn = self._active
        if conn is None:
            return []
        off = conn.offset.offset_us or 0
        key = f"{conn.host}:{conn.port}"
        return [(raw, off, key) for raw in conn.drain_traces()]

    # -- lifecycle -----------------------------------------------------------
    def connect(self) -> None:
        """Establish the first connection (rotating through endpoints)."""
        # start the heartbeat scheduler BEFORE dialing: on a degraded
        # start (every endpoint down, fallback != error) the dial raises
        # but the element keeps running, and endpoints watched by later
        # recoveries still need a live scheduler
        if self.monitor is not None:
            self.monitor.start()
        with self._lock:
            self._ensure_active()

    def close(self, send_bye: bool = True) -> None:
        if self.monitor is not None:
            self.monitor.stop()
        with self._lock:
            if self._active is not None:
                self._active.close(send_bye=send_bye)
                self._active = None
                self._active_idx = None
                self._active_key = None

    # -- core ----------------------------------------------------------------
    def _ensure_active(self) -> QueryConnection:
        """Return a live connection, failing over as needed.  Raises
        :class:`CircuitOpenError` when every breaker refuses, or
        ``ConnectionError`` when every admitted endpoint is unreachable."""
        if self._dead.is_set():
            self._demote("heartbeat")
        if self._active is not None:
            return self._active
        last: Optional[BaseException] = None
        all_open = True
        n = len(self.endpoints)
        for off in range(n):
            idx = (self._idx + off) % n
            breaker = self.breakers[idx]
            if not breaker.allow():
                continue
            all_open = False
            host, port = self.endpoints[idx]
            # bound the whole per-endpoint dial loop by the request
            # budget: without the deadline, a blackholed endpoint (SYN
            # dropped) costs max_attempts x connect-timeout per rotation
            # inside chain() before the fallback can fire
            conn = QueryConnection(
                host, port, self.timeout, self.max_retries,
                retry=self.retry.with_deadline(self.timeout),
                qos=self.qos, model=self.model)
            try:
                conn.connect()
            except ConnectionError as exc:
                last = exc
                breaker.record_failure()
                continue
            self._active, self._active_idx, self._idx = conn, idx, idx
            self._active_key = self._key(idx)
            self._dead.clear()
            if self.monitor is not None:
                key = self._key(idx)
                self.monitor.watch(
                    key, lambda c=conn: c.ping(
                        timeout=max(0.1, self.monitor.interval)))
            if off:
                STATS.incr("query.failovers")
            return conn
        if all_open and n:
            raise CircuitOpenError(
                "all endpoints have open circuit breakers: "
                + ", ".join(self._key(i) for i in range(n)))
        raise ConnectionError(
            f"no reachable endpoint among "
            f"{[self._key(i) for i in range(n)]}: {last!r}")

    def _demote(self, reason: str) -> None:
        """Drop the active connection and advance the preference index so
        the next ``_ensure_active`` starts at the following endpoint."""
        if self._active is not None:
            if self.monitor is not None and self._active_idx is not None:
                self.monitor.unwatch(self._key(self._active_idx))
            self._active.close(send_bye=False)
            self._active = None
        if self._active_idx is not None:
            self._idx = (self._active_idx + 1) % len(self.endpoints)
            self._active_idx = None
            self._active_key = None
            if len(self.endpoints) > 1:
                # an alternative exists: this demotion starts a failover
                STATS.incr("query.failovers")
        self._dead.clear()
        STATS.incr(f"query.demotions.{reason}")

    def query(self, buf: TensorBuffer) -> Optional[TensorBuffer]:
        """One frame through the resilient path: per-endpoint breaker
        gating, rotation on failure, backoff between rotations (so a
        mid-stream server kill+restart is survived within the retry
        budget)."""
        last: Optional[BaseException] = None
        #: per-REQUEST budget for honoring retry-after hints: capping
        #: each gap alone would still let max_attempts gaps sum to
        #: multiples of the element timeout
        shed_budget = self.timeout
        for attempt in range(self.retry.max_attempts):
            shed_wait: Optional[float] = None
            with self._lock:
                try:
                    conn = self._ensure_active()
                    # capture the breaker OBJECT, not the index: a
                    # concurrent set_endpoints (the router's rebalance)
                    # may replace/reorder/shrink self.breakers before
                    # this request's outcome lands, and indexing then
                    # would charge the wrong endpoint — or walk off the
                    # end of a shrunken list
                    breaker = self.breakers[self._active_idx]
                except CircuitOpenError:
                    raise                # fail fast: no sleeping on OPEN
                except ConnectionError as exc:
                    last = exc
                    conn = None
            if conn is not None:
                try:
                    out = conn.query(buf)
                    breaker.record_success()
                    return out
                except ShedError as exc:
                    # shed ≠ failure: the server is alive and
                    # protecting itself.  The breaker records SUCCESS
                    # (a shed proves liveness — tripping it would turn
                    # transient overload into a 30 s outage).  With
                    # alternates available, routing away IS honoring
                    # the hint — rotate immediately so a draining or
                    # overloaded primary hands traffic to a healthy
                    # secondary instead of stalling the stream; alone,
                    # honor the retry-after capped by the request
                    # budget (a drain-sized hint must not block
                    # chain() for multiples of the element timeout).
                    last = exc
                    breaker.record_success()
                    if len(self.endpoints) > 1:
                        with self._lock:
                            self._demote("shed")
                    elif self._shed_passthrough:
                        # forwarding mode (fleet router): no alternate
                        # can absorb this — hand the worker's own shed
                        # verdict to the caller NOW; sleeping out the
                        # retry-after in a proxy would just disguise it
                        # as latency
                        raise
                    elif shed_budget <= 0:
                        raise          # budget spent honoring hints
                    else:
                        shed_wait = min(exc.retry_after_s, shed_budget)
                        shed_budget -= shed_wait
                except self._FAILURE as exc:
                    last = exc
                    breaker.record_failure()
                    STATS.incr("query.failures")
                    with self._lock:
                        self._demote("error")
            if attempt + 1 < self.retry.max_attempts:
                STATS.incr("query.retries")
                delay = self.retry.delay(attempt)
                if shed_wait is not None:
                    delay = max(delay, shed_wait)
                # retry-after-honoring backoff (delay from the policy,
                # floored by the server's T_SHED hint)
                time.sleep(delay)   # nnslint: allow(sleep-poll)
        if isinstance(last, (TimeoutError, ConnectionError, OSError)):
            raise last
        if last is not None:
            # e.g. AttributeError from a connection closed under us:
            # normalize so chain()'s fallback handling (which catches
            # the transport error types only) always sees it
            raise ConnectionError(f"query failed: {last!r}") from last
        raise ConnectionError("query failed: no endpoint available")


def parse_endpoints(spec: str, default_host: str = "127.0.0.1"
                    ) -> List[Tuple[str, int]]:
    """``host:port,host2:port2,…`` → ordered endpoint list (a bare
    ``port`` entry takes ``default_host``)."""
    out: List[Tuple[str, int]] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if not sep:
            host, port = default_host, part
        if not port.isdigit():
            raise ValueError(f"dest-hosts: malformed entry {part!r} "
                             "(want host:port)")
        out.append((host or default_host, int(port)))
    if not out:
        raise ValueError(f"dest-hosts: no endpoints in {spec!r}")
    return out


@register_element
class TensorQueryClient(Element):
    FACTORY = "tensor_query_client"
    PROPERTIES = {
        "host": ("127.0.0.1", "server host (reference: the client's "
                              "own bind address; kept as the server "
                              "fallback when dest-* is unset)"),
        "port": (0, "server port (fallback when dest-port unset)"),
        "dest-host": (None, "server host (TCP) or MQTT broker host "
                            "(HYBRID) — the reference's addressing: "
                            "every ssat line uses dest-host/dest-port"),
        "dest-port": (None, "server/broker port"),
        "dest-hosts": (None, "ordered failover list "
                             "'host:port,host2:port2' — overrides "
                             "dest-host/dest-port; the client serves "
                             "from the first live endpoint and fails "
                             "over down the list"),
        "connect-type": ("tcp", "TCP | HYBRID (reference nicks; hybrid "
                                "discovers the data address from the "
                                "retained MQTT record for the topic)"),
        "topic": (None, "hybrid: discovery topic"),
        "timeout": (10.0, "reply timeout seconds (per-request budget "
                          "covering send + reconnect + reply)"),
        "max-retries": (3, "connect retries"),
        "retry": (None, "retry policy spec 'attempts=4,base=0.05,"
                        "cap=0.5,mult=2,jitter=0.25[,deadline=S]' "
                        "(exponential backoff + jitter)"),
        "fallback": ("error", "what a frame does when the remote is "
                              "down: error | passthrough | drop"),
        "breaker-failures": (5, "consecutive failures that OPEN an "
                                "endpoint's circuit breaker"),
        "breaker-cooldown": (30.0, "seconds an OPEN breaker waits "
                                   "before a half-open trial"),
        "heartbeat-interval": (0.0, "seconds between T_PING heartbeats "
                                    "on the active endpoint (0 = "
                                    "disabled); a dead verdict fails "
                                    "over to the next dest-hosts entry"),
        "heartbeat-max-missed": (3, "missed pongs before an endpoint "
                                    "is declared dead"),
        "qos": (None, "QoS class declared to the server in the "
                      "handshake: gold | silver | bronze (admission "
                      "control sheds bronze first, gold last — "
                      "query/overload.py).  Unset: inherited from the "
                      "first frame's nns_class tag, else the server's "
                      "silver default"),
        "model": (None, "model identity declared in the handshake "
                        "(fleet/router.py): a tensor_query_router "
                        "endpoint consistent-hashes it so this "
                        "stream's frames land on the same workers as "
                        "every other stream of the model — per-model "
                        "cross-stream buckets stay dense.  Plain "
                        "servers ignore it"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")
        self.add_src_pad(tensors_template_caps(), "src")

    def _server_address(self) -> "tuple[str, int]":
        """Resolve the data-channel address the reference way: HYBRID
        looks up the retained record for the topic on the MQTT broker
        at dest-host:dest-port (tensor_query_client.c via
        nnstreamer-edge); TCP takes dest-host:dest-port directly, with
        the legacy host/port pair as fallback."""
        if str(self.connect_type).lower() == "hybrid":
            from .mqtt import fetch_retained_record

            if self.topic in (None, ""):
                raise ValueError(f"{self.name}: connect-type=HYBRID "
                                 "requires topic")
            broker_host = str(self.dest_host or "127.0.0.1")
            # port 0 is never a routable broker port: 0/unset both
            # mean "default" # nnslint: allow(falsy-zero-default)
            broker_port = int(self.dest_port or 1883)
            record = fetch_retained_record(
                broker_host, broker_port, f"nns/query/{self.topic}",
                float(self.timeout), f"nns-query-cli-{self.name}")
            if not record:
                raise ConnectionError(
                    f"{self.name}: no retained discovery record for "
                    f"topic {self.topic!r} on "
                    f"{broker_host}:{broker_port}")
            host, sep, port = record.decode().rpartition(":")
            if not sep or not port.isdigit():
                raise ConnectionError(
                    f"{self.name}: malformed discovery record "
                    f"{record!r} (want host:port)")
            return host, int(port)
        if self.dest_port not in (None, "", 0):
            return str(self.dest_host or "127.0.0.1"), int(self.dest_port)
        if self.dest_host not in (None, ""):
            # silently connecting to the legacy host/port when only
            # dest-host was given would hit the wrong machine
            raise ValueError(f"{self.name}: dest-host={self.dest_host!r} "
                             "needs dest-port")
        return str(self.host), int(self.port)

    def _endpoints(self) -> List[Tuple[str, int]]:
        if self.dest_hosts not in (None, ""):
            return parse_endpoints(str(self.dest_hosts))
        return [self._server_address()]

    def start(self):
        self._fallback = str(self.fallback or "error").lower()
        if self._fallback not in ("error", "passthrough", "drop"):
            raise ValueError(f"{self.name}: fallback={self.fallback!r} "
                             "(want error | passthrough | drop)")
        qos = None
        if self.qos not in (None, ""):
            qos = qos_of_class(self.qos)
            if qos is None:
                raise ValueError(f"{self.name}: qos={self.qos!r} "
                                 "(want gold | silver | bronze)")
        self.conn = FailoverConnection(
            self._endpoints(), float(self.timeout),
            int(self.max_retries),
            # an explicit retry spec wins; otherwise keep the documented
            # max-retries contract (parse(None) would be a truthy
            # 4-attempt default and silently override the property)
            retry=(RetryPolicy.parse(self.retry)
                   if self.retry not in (None, "") else None),
            breaker_failures=int(self.breaker_failures),
            breaker_cooldown=float(self.breaker_cooldown),
            heartbeat_interval=float(self.heartbeat_interval),
            heartbeat_max_missed=int(self.heartbeat_max_missed),
            name=self.name,
            qos=qos,
            model=(str(self.model) if self.model not in (None, "")
                   else None))
        try:
            self.conn.connect()
        except ConnectionError:
            if self._fallback == "error":
                raise
            # degraded start (reference graceful-degradation story):
            # stream flows via the fallback while the remote is down;
            # queries keep probing the endpoints each frame
            from ..utils.log import logger

            STATS.incr("query.degraded_starts")
            logger.warning("%s: no endpoint reachable at start; "
                           "running with fallback=%s", self.name,
                           self._fallback)

    def stop(self):
        conn = getattr(self, "conn", None)
        if conn is not None:
            conn.close()

    def health_state(self):
        conn = getattr(self, "conn", None)
        if conn is not None and conn.degraded():
            return "degraded"
        return None

    def set_caps(self, pad, caps):
        # announce the server's answer caps when it advertised them,
        # else assume passthrough shape (a degraded start has no server
        # caps yet; chain() re-announces once a recovery learns them)
        sc = self.conn.server_caps
        self._announced_server_caps = bool(sc)
        self._sink_caps_str = str(caps)
        if sc:
            self.announce_src_caps(Caps.from_string(sc))
        else:
            super().set_caps(pad, caps)

    def _passthrough_safe(self) -> bool:
        """May an input frame be pushed downstream as-is?  Only when the
        downstream negotiation wasn't built on server answer caps that
        differ from the input caps — otherwise passthrough would hand a
        wrongly-shaped buffer to elements expecting the server output."""
        if not getattr(self, "_announced_server_caps", False):
            return True
        sc, sk = self.conn.server_caps, getattr(self, "_sink_caps_str", None)
        if not sc or not sk:
            return True
        return str(Caps.from_string(sc)) == str(Caps.from_string(sk))

    def _stamp_trace(self, buf, tracer) -> None:
        """Attach the wire trace context (obs/span.py) so the serving
        pipeline's spans land under THIS run's trace id.  origin_us is
        the buffer's source stamp re-based onto the wall clock — the
        cross-process interlatency origin."""
        if "nns_trace" in buf.extra:
            return
        from ..obs.span import new_trace_id

        src_ns = buf.extra.get("nns_src_ns")
        if src_ns is not None:
            origin = (tracer.anchor_wall_us
                      + (src_ns - tracer.anchor_mono_ns) // 1000)
        else:
            origin = wall_us()
        buf.extra["nns_trace"] = TraceContext(tracer.trace_id,
                                              new_trace_id(), origin)

    def _merge_remote_spans(self, tracer) -> None:
        import json as _json

        for raw, off, key in self.conn.drain_remote_traces():
            try:
                payload = _json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            tracer.add_remote_spans(payload, offset_us=off,
                                    process=f"server:{key}")

    def chain(self, pad, buf):
        tracer = (self.pipeline.tracer
                  if self.pipeline is not None else None)
        if tracer is not None:
            self._stamp_trace(buf, tracer)
        try:
            out = self.conn.query(buf)
        except (TimeoutError, ConnectionError, OSError) as exc:
            # satellite fix: a reply timeout (or a dead endpoint) maps to
            # the element's fallback policy instead of escaping the
            # streaming thread as a raw exception
            STATS.incr("query.fallbacks")
            if self._fallback == "passthrough":
                if self._passthrough_safe():
                    return self.push(buf)
                # shapes differ: degrade to drop rather than push an
                # input-shaped buffer through a downstream negotiated
                # for the server's answer caps
                from ..utils.log import logger

                logger.warning("%s: fallback=passthrough unsafe (server "
                               "caps differ from input); dropping frame",
                               self.name)
                return FlowReturn.DROPPED
            if self._fallback == "drop":
                return FlowReturn.DROPPED
            raise ConnectionError(
                f"{self.name}: query failed with fallback=error: "
                f"{exc!r}") from exc
        if out is None:
            return FlowReturn.ERROR
        if tracer is not None:
            # refresh the clock offset from a ping sample (unbiased by
            # model latency; rate-limited inside), then harvest the
            # server's T_TRACE piggyback into one merged timeline
            self.conn.sample_clock_offset()
            self._merge_remote_spans(tracer)
        if not getattr(self, "_announced_server_caps", True):
            # degraded start negotiated the passthrough shape; the
            # recovery that served this frame learned the server's real
            # answer caps — renegotiate downstream before pushing
            sc = self.conn.server_caps
            if sc:
                self._announced_server_caps = True
                self.announce_src_caps(Caps.from_string(sc))
        return self.push(out)
