"""Shared-memory ring transport: host-local single-copy tensor streams.

The reference's inter-pipeline transports are all socket wires — TCP
query (`gst/nnstreamer/tensor_query/`), MQTT, gRPC — so two pipelines
on ONE host still pay the kernel socket path per buffer.  On a TPU host
feeding tens of kfps that's the wrong transport; this module gives
co-located pipelines a lock-free SPSC ring through POSIX shared memory:

    producer: … ! tensor_shm_sink path=frames
    consumer: tensor_shm_src path=frames ! …

Record payloads use the same tensor framing as the TCP wire
(`protocol.encode_tensors`), so static and flexible streams both ride
the ring.  Caps negotiate through the ring header (producer writes the
caps string; consumer's ``negotiate`` reads it) — the role of the TCP
HELLO exchange.

Two interoperable implementations of one region layout (documented in
native/tensorwire/shmring.cc): the C++ ring via ctypes when the native
lib is available, else a pure-Python mmap fallback (adequate for tests
and toolchain-less hosts; the native path is the fast one).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import struct
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..analysis.sanitizer import make_condition, make_lock
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..pipeline.tracing import record_copy
from ..tensor.buffer import (BufferLease, TensorBuffer, TensorBufferPool,
                             default_pool)
from ..tensor.caps_util import tensors_template_caps
from .protocol import decode_tensors, tensor_parts

# region layout constants — must match native/tensorwire/shmring.cc
_MAGIC = 0x4E545352  # 'NTSR'
_VERSION = 1
_CAPS_MAX = 4096
_OFF_CAPS = 24
_OFF_HEAD = 4160
_OFF_TAIL = 4224
_OFF_EOS = 4288
_OFF_SLOTS = 4352
_SLOT_HDR = 16  # u64 len + s64 pts

DEFAULT_SLOT_BYTES = 1 << 20
DEFAULT_SLOTS = 16

# -- process-local ring wakeups (pure-Python fallback) ----------------------
# A blocked pure-Python endpoint cannot be notified by a REMOTE process
# (no futex without the native lib), but the common test/bench topology
# runs both pipelines in ONE process.  Rings share a per-name condition:
# push/pop/eos notify it, so a same-process peer wakes immediately
# (event-driven, zero idle wakeups) while a cross-process peer degrades
# to the bounded timed re-check of the wait loop — never a busy spin.
_WAKEUPS: Dict[str, "tuple[threading.Condition, int]"] = {}
_WAKEUPS_LOCK = make_lock("leaf")


def _wakeup_acquire(name: str) -> threading.Condition:
    with _WAKEUPS_LOCK:
        cond, refs = _WAKEUPS.get(name, (None, 0))
        if cond is None:
            cond = make_condition("shm.ring")
        _WAKEUPS[name] = (cond, refs + 1)
        return cond


def _wakeup_release(name: str) -> None:
    with _WAKEUPS_LOCK:
        cond, refs = _WAKEUPS.get(name, (None, 0))
        if cond is None:
            return
        if refs <= 1:
            del _WAKEUPS[name]
        else:
            _WAKEUPS[name] = (cond, refs - 1)


def _native_lib():
    from .. import native

    lib = native._load()
    if lib is None or not hasattr(lib, "tw_shm_create"):
        return None
    if not getattr(lib, "_shm_bound", False):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.tw_shm_create.restype = ctypes.c_void_p
        lib.tw_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint32, ctypes.c_char_p]
        lib.tw_shm_open.restype = ctypes.c_void_p
        lib.tw_shm_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.tw_shm_caps.restype = ctypes.c_uint32
        lib.tw_shm_caps.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint32]
        lib.tw_shm_push.restype = ctypes.c_int
        lib.tw_shm_push.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64,
                                    ctypes.c_int64, ctypes.c_uint32]
        if hasattr(lib, "tw_shm_push2"):
            lib.tw_shm_push2.restype = ctypes.c_int
            lib.tw_shm_push2.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
                ctypes.c_int64, ctypes.c_uint32]
        lib.tw_shm_pop.restype = ctypes.c_int64
        lib.tw_shm_pop.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64,
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.c_uint32]
        lib.tw_shm_eos.argtypes = [ctypes.c_void_p]
        lib.tw_shm_slot_size.restype = ctypes.c_uint64
        lib.tw_shm_slot_size.argtypes = [ctypes.c_void_p]
        lib.tw_shm_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib._shm_bound = True
    return lib


class ShmRing:
    """One endpoint of the ring; ``create=True`` = producer side."""

    def __init__(self, name: str, create: bool,
                 slot_bytes: int = DEFAULT_SLOT_BYTES,
                 n_slots: int = DEFAULT_SLOTS, caps: str = "",
                 timeout: float = 10.0):
        if not name.startswith("/"):
            name = "/" + name
        self.name = name
        if create and len(caps.encode()) > _CAPS_MAX:
            # uniform, descriptive reject on BOTH paths (the native
            # tw_shm_create would return nullptr -> opaque ConnectionError)
            raise ValueError(
                f"shm ring {name!r}: caps string {len(caps.encode())} B "
                f"exceeds {_CAPS_MAX} B header slot")
        self._lib = _native_lib()
        self._h = None
        self._mm: Optional[mmap.mmap] = None
        self._wake: Optional[threading.Condition] = None
        self._owner = create
        if self._lib is not None:
            if create:
                self._h = self._lib.tw_shm_create(
                    name.encode(), slot_bytes, n_slots, caps.encode())
            else:
                self._h = self._lib.tw_shm_open(
                    name.encode(), int(timeout * 1000))
            if not self._h:
                raise ConnectionError(f"shm ring {name!r}: "
                                      f"{'create' if create else 'open'} "
                                      "failed")
            self.slot_bytes = int(self._lib.tw_shm_slot_size(self._h))
        else:
            self._py_init(create, slot_bytes, n_slots, caps, timeout)

    # -- pure-Python fallback (same layout).  SAFETY: cross-process
    # correctness relies on x86-64 TSO (stores retire in order) and on
    # aligned 8-byte mmap writes being single stores — CPython emits no
    # fences.  On other ISAs (aarch64) a consumer could observe the head
    # advance before the payload lands; warn loudly there and prefer the
    # native ring (its C++11 atomics are correct everywhere). ------------
    def _py_init(self, create, slot_bytes, n_slots, caps, timeout):
        import platform

        if platform.machine() not in ("x86_64", "AMD64"):
            from ..utils.log import logger

            logger.warning(
                "shm ring %s: pure-Python fallback has no memory barriers "
                "— cross-process use on %s may tear records; build the "
                "native lib (make -C native)", self.name,
                platform.machine())
        path = "/dev/shm" + self.name
        if create:
            caps_b = caps.encode()  # <= _CAPS_MAX, checked in __init__
            total = _OFF_SLOTS + n_slots * (_SLOT_HDR + slot_bytes)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.truncate(total)
            os.replace(tmp, path)
            self._fd = os.open(path, os.O_RDWR)
            self._mm = mmap.mmap(self._fd, total)
            self._mm[8:16] = struct.pack("<Q", slot_bytes)
            self._mm[16:24] = struct.pack("<II", n_slots, len(caps_b))
            self._mm[_OFF_CAPS:_OFF_CAPS + len(caps_b)] = caps_b
            # magic last (consumer spins on it)
            self._mm[0:8] = struct.pack("<II", _MAGIC, _VERSION)
        else:
            deadline = time.monotonic() + timeout
            while True:
                try:
                    self._fd = os.open(path, os.O_RDWR)
                    st = os.fstat(self._fd)
                    if st.st_size >= _OFF_SLOTS:
                        self._mm = mmap.mmap(self._fd, st.st_size)
                        magic, ver = struct.unpack("<II", self._mm[0:8])
                        if magic == _MAGIC and ver == _VERSION:
                            break
                        self._mm.close()
                        self._mm = None
                        if magic == _MAGIC:  # right ring, wrong layout
                            os.close(self._fd)
                            raise ConnectionError(
                                f"shm ring {self.name!r}: version {ver} "
                                f"!= {_VERSION}")
                    os.close(self._fd)
                except FileNotFoundError:
                    # only "not created yet" retries; anything else —
                    # including the version-mismatch ConnectionError
                    # (an OSError subclass!) — must escape, not spin
                    # into a misleading "open timed out"
                    pass
                if time.monotonic() > deadline:
                    raise ConnectionError(f"shm ring {self.name!r}: "
                                          "open timed out")
                # cross-PROCESS file-appearance wait: no local producer
                # exists yet to signal, so a timed re-check is the only
                # pure-Python option  # nnslint: allow(sleep-poll)
                time.sleep(0.002)
        self.slot_bytes = struct.unpack("<Q", self._mm[8:16])[0]
        self._n_slots = struct.unpack("<I", self._mm[16:20])[0]
        self._wake = _wakeup_acquire(self.name)

    def _py_u64(self, off: int) -> int:
        return struct.unpack("<Q", self._mm[off:off + 8])[0]

    # Blocked-side waiting (pure-Python fallback): condition-driven.
    # ``_wait_change`` blocks on the ring's process-local condition, so a
    # same-process peer's push/pop/eos wakes it IMMEDIATELY; the timeout
    # only bounds the re-check for cross-process peers (which cannot
    # notify) — exponential 50 µs → 2 ms, the pacing of shmring.cc's
    # native backoff.  This replaces the time.sleep backoff loop (and
    # before that a flat 100 µs spin), so a local stall costs zero
    # wakeups instead of 500+/s.
    def _wait_change(self, blocked, deadline: float, delay: float,
                     stalled: str) -> float:
        """One bounded wait while ``blocked()`` holds; raises
        TimeoutError(``stalled``) past ``deadline``.  Returns the next
        re-check delay.  The blocked-state re-check happens UNDER the
        condition, so a local peer's notify between check and wait is
        never lost."""
        with self._wake:
            if not blocked():
                return delay
            if time.monotonic() > deadline:
                raise TimeoutError(stalled)
            self._wake.wait(delay)
        return delay * 2 if delay < 0.002 else delay

    def _notify(self) -> None:
        """Ring state changed (slot filled/freed, EOS): wake any
        same-process peer blocked in ``_wait_change``."""
        wake = self._wake
        if wake is not None:
            with wake:
                wake.notify_all()

    # -- API -------------------------------------------------------------
    def caps(self) -> str:
        if self._lib is not None:
            out = ctypes.create_string_buffer(_CAPS_MAX)
            n = self._lib.tw_shm_caps(self._h, out, _CAPS_MAX)
            return out.raw[:n].decode()
        n = struct.unpack("<I", self._mm[20:24])[0]
        return bytes(self._mm[_OFF_CAPS:_OFF_CAPS + n]).decode()

    def push(self, payload: bytes, pts: int, timeout: float = 10.0) -> None:
        self.push_parts([payload], pts, timeout)

    def push_parts(self, parts, pts: int, timeout: float = 10.0) -> None:
        """Scatter-gather push: writes the iovec straight into the slot
        — ONE copy from the tensor views to shared memory, no staging
        blob (the old ``push(encode_tensors(buf))`` paid two)."""
        arrs = [np.frombuffer(p, np.uint8) for p in parts]
        total = sum(a.nbytes for a in arrs)
        record_copy(total)   # the slot write is the transport's one copy
        if self._lib is not None and hasattr(self._lib, "tw_shm_push2"):
            n = len(arrs)
            ptrs = (ctypes.c_void_p * n)(
                *(a.ctypes.data for a in arrs))
            lens = (ctypes.c_uint64 * n)(*(a.nbytes for a in arrs))
            rc = self._lib.tw_shm_push2(self._h, ptrs, lens, n, pts,
                                        int(timeout * 1000))
            if rc == -2:
                raise ValueError(f"record {total} B exceeds slot "
                                 f"size {self.slot_bytes}")
            if rc != 0:
                raise TimeoutError("shm ring full (consumer stalled?)")
            return
        if self._lib is not None:
            # old .so without the scatter entry: push a single part
            # zero-copy (the pre-scatter behavior); stage only when
            # there is genuinely more than one part to gather
            if len(arrs) == 1:
                flat, blob_len = arrs[0], arrs[0].nbytes
                buf = flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            else:
                blob = b"".join(a.tobytes() for a in arrs)
                blob_len = len(blob)
                buf = ctypes.cast(ctypes.c_char_p(blob),
                                  ctypes.POINTER(ctypes.c_uint8))
            rc = self._lib.tw_shm_push(self._h, buf, blob_len, pts,
                                       int(timeout * 1000))
            if rc == -2:
                raise ValueError(f"record {total} B exceeds slot "
                                 f"size {self.slot_bytes}")
            if rc != 0:
                raise TimeoutError("shm ring full (consumer stalled?)")
            return
        if total > self.slot_bytes:
            raise ValueError(f"record {total} B exceeds slot "
                             f"size {self.slot_bytes}")
        deadline = time.monotonic() + timeout
        delay = 5e-5

        def _full() -> bool:
            return (self._py_u64(_OFF_HEAD) - self._py_u64(_OFF_TAIL)
                    >= self._n_slots)

        while _full():
            delay = self._wait_change(
                _full, deadline, delay,
                "shm ring full (consumer stalled?)")
        head = self._py_u64(_OFF_HEAD)
        off = _OFF_SLOTS + (head % self._n_slots) * (_SLOT_HDR
                                                    + self.slot_bytes)
        self._mm[off:off + 16] = struct.pack("<Qq", total, pts)
        pos = off + 16
        for a in arrs:
            self._mm[pos:pos + a.nbytes] = a.data
            pos += a.nbytes
        self._mm[_OFF_HEAD:_OFF_HEAD + 8] = struct.pack("<Q", head + 1)
        self._notify()   # slot filled: wake a same-process consumer

    def pop(self, timeout: float = 10.0
            ) -> Optional[Tuple[bytes, int]]:
        """(payload, pts) — or None on EOS-and-drained."""
        got = self.pop_into(None, timeout)
        if got is None:
            return None
        lease, n, pts = got
        payload = bytes(lease.memory()[:n])
        lease.release()
        return payload, pts

    def pop_into(self, pool: Optional[TensorBufferPool],
                 timeout: float = 10.0
                 ) -> Optional[Tuple[BufferLease, int, int]]:
        """Pop the next record into a pooled slab: ``(lease, nbytes,
        pts)`` — or None on EOS-and-drained.  ONE copy out of the ring;
        the consumer decodes zero-copy views over the lease."""
        if pool is None:
            pool = default_pool()
        if self._lib is not None:
            # full-slot-capacity lease (record length unknown until the
            # native pop); exact-size bucketing still recycles it
            lease = pool.acquire(self.slot_bytes)
            dst = np.frombuffer(lease.memory(), np.uint8)
            pts = ctypes.c_int64()
            n = self._lib.tw_shm_pop(
                self._h, dst.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint8)),
                self.slot_bytes, ctypes.byref(pts), int(timeout * 1000))
            del dst
            if n == -3:
                lease.release()
                return None
            if n < 0:
                lease.release()
                raise TimeoutError("shm ring empty (producer stalled?)")
            return lease, int(n), pts.value
        deadline = time.monotonic() + timeout
        delay = 5e-5

        def _empty() -> bool:
            return self._py_u64(_OFF_HEAD) == self._py_u64(_OFF_TAIL)

        while _empty():
            if struct.unpack("<I", self._mm[_OFF_EOS:_OFF_EOS + 4])[0]:
                return None
            delay = self._wait_change(
                _empty, deadline, delay,
                "shm ring empty (producer stalled?)")
        tail = self._py_u64(_OFF_TAIL)
        off = _OFF_SLOTS + (tail % self._n_slots) * (_SLOT_HDR
                                                     + self.slot_bytes)
        ln, pts = struct.unpack("<Qq", self._mm[off:off + 16])
        lease = pool.acquire(ln)
        lease.memory()[:] = self._mm[off + 16:off + 16 + ln]
        self._mm[_OFF_TAIL:_OFF_TAIL + 8] = struct.pack("<Q", tail + 1)
        self._notify()   # slot freed: wake a same-process producer
        return lease, ln, pts

    def eos(self) -> None:
        if self._lib is not None:
            self._lib.tw_shm_eos(self._h)
        else:
            self._mm[_OFF_EOS:_OFF_EOS + 4] = struct.pack("<I", 1)
            self._notify()   # consumers blocked on empty re-check EOS

    def close(self, unlink: Optional[bool] = None) -> None:
        """Unmap; unlink the shm name when ``unlink`` (default: consumer
        side).  The producer must NOT unlink at close — a consumer that
        attaches late still needs to drain the ring; ``create`` replaces
        any stale ring left behind, bounding the leak to one name."""
        if unlink is None:
            unlink = not self._owner
        if self._lib is not None:
            if self._h:
                self._lib.tw_shm_close(self._h, 1 if unlink else 0)
                self._h = None
            return
        if self._mm is not None:
            self._mm.close()
            self._mm = None
            os.close(self._fd)
            if self._wake is not None:
                self._notify()   # peers re-check state one last time
                _wakeup_release(self.name)
                self._wake = None
            if unlink:
                try:
                    os.unlink("/dev/shm" + self.name)
                except OSError:
                    pass

    @property
    def is_native(self) -> bool:
        return self._lib is not None


@register_element
class ShmSink(Element):
    """Publish the stream into a shared-memory ring (host-local
    single-copy transport; see module docstring)."""

    FACTORY = "tensor_shm_sink"
    PROPERTIES = {
        "path": ("nns-shm", "shm ring name (under /dev/shm)"),
        "slot-bytes": (DEFAULT_SLOT_BYTES, "max record size"),
        "slots": (DEFAULT_SLOTS, "ring capacity in records"),
        "timeout": (10.0, "push timeout (s) when the ring is full"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def start(self):
        self._ring: Optional[ShmRing] = None

    def stop(self):
        if self._ring is not None:
            self._ring.eos()
            self._ring.close()
            self._ring = None

    def set_caps(self, pad, caps):
        # ring is created at caps time so the consumer's negotiate() can
        # read them from the header (the TCP path's HELLO role)
        if self._ring is None:
            self._ring = ShmRing(str(self.path), create=True,
                                 slot_bytes=int(self.slot_bytes),
                                 n_slots=int(self.slots), caps=str(caps))
            self._ring_caps = str(caps)
        elif str(caps) != self._ring_caps:
            # the header caps are the consumer's negotiation source; a
            # silent mid-stream change would let differently-shaped
            # records flow under stale caps
            raise RuntimeError(
                f"{self.name}: caps renegotiation after ring creation is "
                f"not supported (ring header holds {self._ring_caps!r}); "
                "stop/start the sink to change caps")

    def chain(self, pad, buf):
        if self._ring is None:
            # caps always precede data in this framework (set_caps creates
            # the ring); a buffer without caps is a bug upstream — fail
            # loudly rather than publish an un-negotiable capsless ring
            raise RuntimeError(f"{self.name}: buffer before caps")
        # scatter-gather: tensor views land in the slot directly (one
        # copy into shared memory, no staging blob)
        parts = tensor_parts(buf)
        ctx = buf.extra.get("nns_trace")
        if ctx is not None and ctx.trace_id:
            # trace context rides a self-identifying trailer AFTER the
            # tensors (obs/span.py): the fixed 16-byte slot header is
            # shared with the native ring and cannot grow, and
            # decode_tensors never reads past the declared tensors, so
            # context-unaware consumers are unaffected.  A frame sized
            # right up to slot-bytes ships WITHOUT the trailer instead
            # of erroring: attaching a tracer must never turn a working
            # pipeline into a failing one.
            from ..obs.span import TRAILER_SIZE, pack_ctx_trailer

            total = sum(len(p) if isinstance(p, bytes) else p.nbytes
                        for p in parts)
            if total + TRAILER_SIZE <= self._ring.slot_bytes:
                parts.append(pack_ctx_trailer(ctx))
        self._ring.push_parts(parts, buf.pts or 0,
                              float(self.timeout))
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            if self._ring is not None:
                self._ring.eos()
            self.post_eos_reached()


@register_element
class ShmSrc(Source):
    """Consume a shared-memory ring published by ``tensor_shm_sink``."""

    FACTORY = "tensor_shm_src"
    PROPERTIES = {
        "path": ("nns-shm", "shm ring name (under /dev/shm)"),
        "caps": (None, "override caps (else the ring header's)"),
        "timeout": (10.0, "open/pop timeout (s)"),
        "num-buffers": (-1, "stop after N buffers, -1 unlimited"),
        "prefetch": (0, "drain the ring from a reader thread into an "
                        "unbounded local fifo (1 = on).  Decouples the "
                        "producer from this pipeline's processing rate "
                        "— the same structure edge_src/tensor_query use "
                        "— at the cost of unbounded consumer-side "
                        "memory.  0 (default) pops on demand, keeping "
                        "the ring's bounded-backpressure contract"),
    }

    #: in-band wake marker for the blocking prefetch-fifo get in
    #: create() (AppSrc._WAKE treatment: teardown enqueues it instead of
    #: the reader polling with a timeout)
    _WAKE = object()

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        self._ring: Optional[ShmRing] = None
        self._count = 0
        self._pool = default_pool()
        self._fifo = None
        self._reader = None

    def unblock(self):
        if self._fifo is not None:
            self._fifo.put(self._WAKE)

    def _halt(self) -> None:
        # flag before marker, AppSrc-style: a create() that consumes the
        # marker must observe halted and exit
        self._halted.set()
        if self._fifo is not None:
            self._fifo.put(self._WAKE)
        super()._halt()

    def stop(self):
        self._halt()
        if self._reader is not None:
            self._reader.join(timeout=10)
            self._reader = None
        if self._ring is not None:
            self._ring.close()   # consumer side unlinks
            self._ring = None

    def negotiate(self) -> Caps:
        # the blocking ring-open happens HERE, on the streaming thread —
        # start() runs synchronously inside Pipeline.play(), and a
        # not-yet-up producer must not stall the whole pipeline's startup
        self._ring = ShmRing(str(self.path), create=False,
                             timeout=float(self.timeout))
        if int(self.prefetch or 0):
            import queue as _queue
            import threading

            # bounded upstream by the ring's fixed slot count: the
            # prefetch reader can only get ahead by n_slots frames
            # nnslint: allow(unbounded-queue)
            self._fifo = _queue.Queue()
            self._reader = threading.Thread(
                target=self._drain_loop, daemon=True,
                name=f"shm-src:{self.name}")
            self._reader.start()
        if self.caps:
            c = self.caps
            return Caps.from_string(c) if isinstance(c, str) else c
        caps = self._ring.caps()
        if not caps:
            raise ValueError(f"{self.name}: ring {self.path!r} carries no "
                             "caps; set the caps property")
        return Caps.from_string(caps)

    def _drain_loop(self) -> None:
        """prefetch=1 reader: pop the ring as fast as the producer fills
        it, park records in the local fifo.  The producer never blocks
        on THIS pipeline's processing rate (the decoupling edge_src gets
        from its broker-reader thread)."""
        deadline = time.monotonic() + float(self.timeout)
        while not self._halted.is_set():
            try:
                got = self._ring.pop_into(self._pool, timeout=0.1)
            except TimeoutError:
                if time.monotonic() > deadline:
                    self._fifo.put(TimeoutError(
                        f"{self.name}: no data on ring {self.path!r} "
                        f"for {self.timeout}s and no EOS "
                        "(producer gone?)"))
                    return
                continue
            except Exception as exc:  # noqa: BLE001 — any reader death
                # must surface on the streaming thread, not strand
                # create() polling an empty fifo forever (the on-demand
                # branch propagates the same exception directly)
                self._fifo.put(exc)
                return
            deadline = time.monotonic() + float(self.timeout)
            self._fifo.put(got)
            if got is None:      # EOS and drained
                return

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.num_buffers)
        if n >= 0 and self._count >= n:
            return None
        deadline = time.monotonic() + float(self.timeout)
        while not self._halted.is_set():
            if self._fifo is not None:
                # blocking get, no timeout: the reader thread (or the
                # _halt/unblock wake marker) is the only wake source
                got = self._fifo.get()
                if got is self._WAKE:
                    continue   # teardown marker: re-check halted
                if isinstance(got, BaseException):
                    raise got
            else:
                try:
                    got = self._ring.pop_into(self._pool, timeout=0.1)
                except TimeoutError:
                    # honor the documented bound: a producer that
                    # vanished without EOS must not hang the pipeline
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"{self.name}: no data on ring "
                            f"{self.path!r} for {self.timeout}s and no "
                            "EOS (producer gone?)")
                    continue
            if got is None:
                return None
            lease, n, pts = got
            self._count += 1
            # zero-copy decode over the pooled slab; the lease rides the
            # buffer so the slab outlives every downstream view
            payload = lease.memory()[:n]
            out = TensorBuffer(tensors=decode_tensors(payload), pts=pts,
                               lease=lease)
            from ..obs.span import unpack_ctx_trailer

            ctx = unpack_ctx_trailer(payload)
            if ctx is not None:
                out.extra["nns_trace"] = ctx
            return out
        return None
