"""Resilience substrate for the distributed query layer (L5).

The reference's among-device elements survive flaky edge links with
reconnect loops inside libnnstreamer-edge (nnstreamer-edge/src/
libnnstreamer-edge/nnstreamer-edge-internal.c: connection retries,
keep-alive) — our reproduction centralizes that story in three policies
shared by every transport in ``nnstreamer_tpu.query``:

- :class:`RetryPolicy` — exponential backoff with decorrelated jitter and
  a per-request deadline budget.  Used for connects (client, edge pub/sub,
  gRPC redial) and for send-retry on publisher sockets.
- :class:`CircuitBreaker` — closed/open/half-open with consecutive-failure
  and failure-rate tracking over a sliding window.  One breaker per remote
  endpoint stops a dead server from eating a full timeout per frame.
- :class:`HealthMonitor` — heartbeat scheduler pinging endpoints over the
  wire protocol's ``T_PING``/``T_PONG`` messages; tracks RTT (EWMA) and
  liveness (alive → suspect → dead) per endpoint and fires callbacks on
  state changes, driving multi-endpoint failover in the query client.

Every retry / failure / breaker transition / failover increments a named
counter in :data:`STATS`; :class:`~nnstreamer_tpu.pipeline.tracing.Tracer`
snapshots the counters at attach and reports the per-run delta, so
``launch.py --trace`` surfaces resilience activity next to proctime.

This module depends only on the stdlib (no pipeline imports) so it can be
used from any layer without cycles.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple


class ResilienceStats:
    """Thread-safe named counters (retries, failures, breaker trips…)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def delta(self, since: Dict[str, int]) -> Dict[str, int]:
        """Counters accumulated after ``since`` (a prior snapshot)."""
        now = self.snapshot()
        return {k: v - since.get(k, 0) for k, v in now.items()
                if v - since.get(k, 0)}


#: process-wide counter registry (one query layer per process)
STATS = ResilienceStats()


class RetryExhausted(ConnectionError):
    """All attempts of a :class:`RetryPolicy` run failed (or the deadline
    budget ran out); ``__cause__`` carries the last underlying error."""


class RetryPolicy:
    """Exponential backoff + jitter + per-request deadline budget.

    ``delay(attempt)`` grows ``base * multiplier**attempt`` capped at
    ``max_delay``, each delay randomized by ±``jitter`` fraction (full
    determinism for tests via an injectable ``rng``).  ``run(fn)`` drives
    the whole loop: attempts are bounded by ``max_attempts`` AND by
    ``deadline`` seconds of total elapsed time — whichever is hit first.
    """

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 1.0, multiplier: float = 2.0,
                 jitter: float = 0.25,
                 deadline: Optional[float] = None) -> None:
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = None if deadline is None else float(deadline)

    @classmethod
    def parse(cls, spec: "str | RetryPolicy | None") -> "RetryPolicy":
        """Element-property form: ``attempts=5,base=0.05,cap=1.0,
        mult=2.0,jitter=0.25,deadline=10`` (any subset; unknown keys are
        loud so launch-line typos don't silently change behavior)."""
        if spec is None or spec == "":
            return cls()
        if isinstance(spec, RetryPolicy):
            return spec
        kw: Dict[str, float] = {}
        names = {"attempts": "max_attempts", "base": "base_delay",
                 "cap": "max_delay", "mult": "multiplier",
                 "jitter": "jitter", "deadline": "deadline"}
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition("=")
            if not sep or key.strip() not in names:
                raise ValueError(f"retry spec: bad token {part!r} "
                                 f"(want {'/'.join(names)}=value)")
            kw[names[key.strip()]] = float(val)
        if "max_attempts" in kw:
            kw["max_attempts"] = int(kw["max_attempts"])
        return cls(**kw)

    def with_deadline(self, deadline: float) -> "RetryPolicy":
        """Same policy, bounded by ``deadline`` seconds of total elapsed
        time (the per-request budget form used by reconnect paths)."""
        return RetryPolicy(max_attempts=self.max_attempts,
                           base_delay=self.base_delay,
                           max_delay=self.max_delay,
                           multiplier=self.multiplier,
                           jitter=self.jitter, deadline=deadline)

    def delay(self, attempt: int,
              rng: Callable[[], float] = random.random) -> float:
        d = min(self.base_delay * self.multiplier ** attempt,
                self.max_delay)
        if self.jitter:
            d *= 1.0 - self.jitter + 2.0 * self.jitter * rng()
        return d

    def run(self, fn: Callable[[], object], *,
            retry_on: Tuple[type, ...] = (OSError, ConnectionError,
                                          TimeoutError),
            counter: str = "retry",
            sleep: Callable[[float], None] = time.sleep,
            clock: Callable[[], float] = time.monotonic,
            rng: Callable[[], float] = random.random):
        """Call ``fn`` until it succeeds, backing off between attempts.
        Raises :class:`RetryExhausted` (chained to the last error) when
        attempts or the deadline budget run out."""
        start = clock()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retry_on as exc:  # noqa: PERF203 - retry loop
                last = exc
                STATS.incr(f"{counter}.failures")
                if attempt + 1 >= self.max_attempts:
                    break
                d = self.delay(attempt, rng)
                if (self.deadline is not None
                        and clock() - start + d > self.deadline):
                    break
                STATS.incr(f"{counter}.retries")
                sleep(d)
        raise RetryExhausted(
            f"gave up after {self.max_attempts} attempt(s): "
            f"{last!r}") from last


class CircuitOpenError(ConnectionError):
    """The breaker is OPEN: the endpoint is skipped without a network
    round trip (fail-fast instead of one timeout per frame)."""


class CircuitBreaker:
    """Closed / open / half-open breaker with failure-rate tracking.

    Opens when either ``failure_threshold`` consecutive failures occur or
    the failure fraction over the last ``window`` calls reaches
    ``failure_rate`` (with at least ``window`` samples).  After
    ``cooldown`` seconds an OPEN breaker lets ``half_open_max`` trial
    calls through (HALF_OPEN); a trial success closes it, a trial failure
    re-opens it and restarts the cooldown.  Thread-safe; the clock is
    injectable so tests never sleep.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, failure_threshold: int = 5,
                 failure_rate: float = 0.5, window: int = 10,
                 cooldown: float = 30.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "") -> None:
        self.failure_threshold = int(failure_threshold)
        self.failure_rate = float(failure_rate)
        self.window = int(window)
        self.cooldown = float(cooldown)
        self.half_open_max = int(half_open_max)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._outcomes: Deque[bool] = collections.deque(maxlen=self.window)
        self._consecutive = 0
        self._opened_at = 0.0
        self._trials = 0

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.cooldown):
            self._state = self.HALF_OPEN
            self._trials = 0
            STATS.incr("breaker.half_open")

    def allow(self) -> bool:
        """May a call proceed right now?  (HALF_OPEN admits at most
        ``half_open_max`` concurrent trials.)"""
        with self._lock:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN \
                    and self._trials < self.half_open_max:
                self._trials += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._reset(self.CLOSED)
                STATS.incr("breaker.closed")
                return
            self._consecutive = 0
            self._outcomes.append(True)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._trip()           # trial failed: back to OPEN
                return
            if self._state == self.OPEN:
                return
            self._consecutive += 1
            self._outcomes.append(False)
            rate_tripped = (len(self._outcomes) >= self.window
                            and self._outcomes.count(False)
                            >= self.failure_rate * len(self._outcomes))
            if self._consecutive >= self.failure_threshold or rate_tripped:
                self._trip()

    def _trip(self) -> None:
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._trials = 0
        STATS.incr("breaker.open")

    def _reset(self, state: str) -> None:
        self._state = state
        self._outcomes.clear()
        self._consecutive = 0
        self._trials = 0

    def call(self, fn: Callable[[], object]):
        """Gate ``fn`` through the breaker: raises
        :class:`CircuitOpenError` without calling when disallowed,
        records the outcome otherwise (the original error re-raises)."""
        if not self.allow():
            raise CircuitOpenError(
                f"circuit breaker {self.name or id(self)} is open")
        try:
            result = fn()
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result


class EndpointHealth:
    """Mutable per-endpoint liveness record kept by the monitor."""

    ALIVE, SUSPECT, DEAD = "alive", "suspect", "dead"

    __slots__ = ("state", "rtt_ms", "missed", "pings", "pongs")

    def __init__(self) -> None:
        self.state = self.ALIVE
        self.rtt_ms: Optional[float] = None
        self.missed = 0
        self.pings = 0
        self.pongs = 0

    def as_dict(self) -> Dict[str, object]:
        return {"state": self.state, "rtt_ms": self.rtt_ms,
                "missed": self.missed, "pings": self.pings,
                "pongs": self.pongs}


class HealthMonitor:
    """Heartbeat scheduler: pings each watched endpoint every
    ``interval`` seconds via its registered ``ping_fn`` (which returns
    the RTT in seconds or raises on timeout/failure).

    ``max_missed`` consecutive misses flip the endpoint ALIVE → DEAD
    (passing through SUSPECT) and fire ``on_down(key)``; the first
    successful ping afterwards fires ``on_up(key)``.  RTT is smoothed
    with an EWMA (alpha 0.3) so the report is stable under jitter.
    """

    _EWMA_ALPHA = 0.3

    def __init__(self, interval: float = 1.0, max_missed: int = 3,
                 on_down: Optional[Callable[[str], None]] = None,
                 on_up: Optional[Callable[[str], None]] = None,
                 name: str = "health") -> None:
        self.interval = float(interval)
        self.max_missed = int(max_missed)
        self.on_down = on_down
        self.on_up = on_up
        self.name = name
        self._lock = threading.Lock()
        self._endpoints: Dict[str, Callable[[], float]] = {}
        self._health: Dict[str, EndpointHealth] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def watch(self, key: str, ping_fn: Callable[[], float]) -> None:
        with self._lock:
            self._endpoints[key] = ping_fn
            h = self._health.setdefault(key, EndpointHealth())
            # a (re-)watch is a fresh liveness assumption: without the
            # reset, a record stuck on DEAD from a previous watch could
            # never transition into DEAD again, so on_down would not
            # refire for the endpoint's next death
            h.missed = 0
            h.state = EndpointHealth.ALIVE

    def unwatch(self, key: str) -> None:
        with self._lock:
            self._endpoints.pop(key, None)

    def health(self, key: str) -> Optional[EndpointHealth]:
        with self._lock:
            return self._health.get(key)

    def report(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {k: h.as_dict() for k, h in self._health.items()}

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"heartbeat:{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            for key, ping_fn in list(self._endpoints.items()):
                if self._stop.is_set():
                    return
                self.check_now(key, ping_fn)

    def check_now(self, key: str,
                  ping_fn: Optional[Callable[[], float]] = None) -> bool:
        """One synchronous probe of ``key`` (also used by tests to drive
        the monitor without waiting for the scheduler).  Returns True
        when the endpoint answered."""
        with self._lock:
            fn = ping_fn or self._endpoints.get(key)
            h = self._health.setdefault(key, EndpointHealth())
        if fn is None:
            return False
        try:
            rtt = fn()
        except Exception:  # noqa: BLE001 - any ping failure is a miss
            STATS.incr("heartbeat.missed")
            with self._lock:
                h.pings += 1
                h.missed += 1
                if h.missed >= self.max_missed:
                    went_down = h.state != EndpointHealth.DEAD
                    h.state = EndpointHealth.DEAD
                else:
                    went_down = False
                    if h.state == EndpointHealth.ALIVE:
                        h.state = EndpointHealth.SUSPECT
            if went_down:
                STATS.incr("heartbeat.endpoint_down")
                if self.on_down is not None:
                    self.on_down(key)
            return False
        with self._lock:
            h.pings += 1
            h.pongs += 1
            h.missed = 0
            came_up = h.state == EndpointHealth.DEAD
            h.state = EndpointHealth.ALIVE
            rtt_ms = rtt * 1e3
            h.rtt_ms = (rtt_ms if h.rtt_ms is None else
                        (1 - self._EWMA_ALPHA) * h.rtt_ms
                        + self._EWMA_ALPHA * rtt_ms)
        if came_up:
            STATS.incr("heartbeat.endpoint_up")
            if self.on_up is not None:
                self.on_up(key)
        return True
