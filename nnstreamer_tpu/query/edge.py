"""Edge pub/sub: topic-based tensor stream bridging between pipelines/hosts.

Parity with the reference's edge elements (gst/edge/edge_sink.c /
edge_src.c over libnnstreamer-edge: create handle / set_info(HOST, PORT,
TOPIC, CAPS) / start / connect / send, SURVEY.md §2.7) and the broker role
of its MQTT path — but self-contained: :class:`EdgeBroker` is an in-process
TCP broker (no external mosquitto), and pub/sub frames reuse the query wire
protocol with the topic carried in HELLO.

A publisher pipeline ends in ``edge_sink``; subscriber pipelines start with
``edge_src`` pointed at the same broker host/port/topic.
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Dict, Optional, Set

from ..analysis.sanitizer import make_lock
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import (register_element,
                                 register_element_alias)
from ..tensor.buffer import TensorBuffer, default_pool
from ..tensor.caps_util import tensors_template_caps
from .protocol import (Message, T_BYE, T_DATA, T_HELLO, T_PING, T_PONG,
                       decode_tensors, recv_msg, send_msg, send_msg_zc,
                       send_tensors, shutdown_close)
from .protocol import create_connection as checked_connect
from .resilience import STATS, RetryExhausted, RetryPolicy

#: default reconnect policy for edge pub/sub when the ``retry`` property
#: is unset: the backoff must span a plausible broker restart (seconds),
#: not just a transient send error — parse(None)'s generic 4x50ms-base
#: window (~0.35 s of sleep) would give up before a restarted broker is
#: back, defeating the documented restart survival
_EDGE_RETRY_DEFAULT = RetryPolicy(max_attempts=10, base_delay=0.1,
                                  max_delay=1.0, deadline=10.0)


def _edge_retry(spec) -> RetryPolicy:
    if spec in (None, ""):
        return _EDGE_RETRY_DEFAULT
    return RetryPolicy.parse(spec)


class EdgeBroker:
    """Topic broker: HELLO payload = ``pub:<topic>[|caps]`` or
    ``sub:<topic>``; DATA from publishers fan out to all matching
    subscribers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.host = host
        self.port = self._sock.getsockname()[1]
        self._sock.listen(32)
        self._subs: Dict[str, Set[socket.socket]] = {}
        self._conns: Set[socket.socket] = set()
        self._topic_caps: Dict[str, str] = {}
        # per-subscriber-socket send locks: concurrent publishers must not
        # interleave partial frames on one subscriber stream
        self._send_locks: Dict[socket.socket, threading.Lock] = {}
        self._lock = make_lock("query.registry")
        self._stop = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="edge-broker").start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        role, topic = None, None
        pool = default_pool()
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn, pool=pool)
                except ValueError:   # bad magic / CRC: drop the connection
                    break
                if msg is None or msg.type == T_BYE:
                    break
                if msg.type == T_HELLO:
                    spec = msg.payload.decode()
                    role, _, rest = spec.partition(":")
                    topic, _, caps = rest.partition("|")
                    if role == "sub":
                        with self._lock:
                            self._subs.setdefault(topic, set()).add(conn)
                            slock = self._send_locks[conn] = \
                                make_lock("query.send")
                            retained = self._topic_caps.get(topic, "")
                            # Take this conn's send lock before releasing the
                            # broker lock: a publisher recording new caps B
                            # snapshots subscribers under the broker lock and
                            # then needs this send lock, so it cannot overtake
                            # the retained send — the subscriber always sees
                            # retained-then-B.  The broker lock itself is NOT
                            # held across send_msg: a subscriber with a full
                            # TCP send buffer stalls only its own stream, not
                            # every topic/publisher.
                            if retained:
                                slock.acquire()
                        if retained:
                            try:
                                send_msg(conn, Message(
                                    T_HELLO, payload=retained.encode()))
                            except OSError:
                                break
                            finally:
                                slock.release()
                    elif role == "pub" and caps:
                        with self._lock:
                            self._topic_caps[topic] = caps
                        # push caps to subscribers that arrived first
                        # (MQTT retained-message semantics; closes the
                        # sub-before-pub startup race)
                        self._fanout(topic, Message(T_HELLO,
                                                    payload=caps.encode()))
                elif msg.type == T_PING:
                    # liveness heartbeat: echo seq+payload as PONG (under
                    # the subscriber's send lock so the reply never
                    # interleaves with a fanout frame)
                    with self._lock:
                        slock = self._send_locks.get(conn)
                    # pong wall-clock stamp = unbiased offset sample for
                    # the peer (query/server.py does the same)
                    from ..obs.clock import wall_us

                    pong = Message(T_PONG, seq=msg.seq,
                                   epoch_us=wall_us(),
                                   payload=msg.payload)
                    if slock is None:
                        send_msg(conn, pong)
                    else:
                        with slock:
                            send_msg(conn, pong)
                elif msg.type == T_DATA and role == "pub":
                    self._fanout(topic, msg)
                    if msg.lease is not None:
                        # fan-out copies nothing and keeps no views:
                        # drop the payload view, then release so the
                        # slab recycles for the next recv
                        msg.payload = b""
                        msg.lease.release()
                        msg.lease = None
        finally:
            with self._lock:
                if role == "sub" and topic is not None:
                    self._subs.get(topic, set()).discard(conn)
                    self._send_locks.pop(conn, None)
                self._conns.discard(conn)
            conn.close()

    def _fanout(self, topic: str, msg: Message) -> None:
        with self._lock:
            subs = [(s, self._send_locks.get(s)) for s in
                    self._subs.get(topic, ())]
        for s, slock in subs:
            try:
                # zero-copy relay: header + received payload view as one
                # sendmsg iovec (no pack() flattening per subscriber)
                if slock is None:
                    send_msg_zc(s, msg)
                else:
                    with slock:
                        send_msg_zc(s, msg)
            except OSError:
                with self._lock:
                    self._subs.get(topic, set()).discard(s)
                    self._send_locks.pop(s, None)

    def close(self) -> None:
        """Stop the listener AND drop every live connection: a broker
        "kill" must look like one to its peers immediately (their reads
        see EOF and the publisher/subscriber reconnect paths kick in)
        instead of leaving half-dead links blocked in recv."""
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            # shutdown-then-close: a plain close of a socket another
            # thread is blocked reading sends no FIN, so peers would
            # never notice the kill AND the dead conns would keep
            # squatting on the listener's port (protocol.py)
            shutdown_close(c)


_BROKERS: Dict[int, EdgeBroker] = {}
_BROKERS_LOCK = make_lock("leaf")


def get_broker(port: int = 0, host: str = "127.0.0.1") -> EdgeBroker:
    """Start (or reuse) an in-process broker."""
    with _BROKERS_LOCK:
        if port and port in _BROKERS:
            return _BROKERS[port]
        broker = EdgeBroker(host, port)
        _BROKERS[broker.port] = broker
        return broker


def shutdown_brokers() -> None:
    with _BROKERS_LOCK:
        for b in _BROKERS.values():
            b.close()
        _BROKERS.clear()



def _resolve_reference_dest(el) -> str:
    """Reference addressing (edge_sink.c/edge_src.c): dest-host/
    dest-port name the broker the element connects to — the TCP data
    broker for connect-type=TCP, the MQTT broker for HYBRID — and the
    connect-type nick is spelled UPPER-case in every ssat line.  Maps
    dest-* onto the canonical host/port or mqtt-host/mqtt-port pair
    and returns the normalized connect type ('aitt' is the dropped
    Tizen-only transport: a named error, not a silent TCP fallback)."""
    ctype = str(el.connect_type or "tcp").strip().lower()
    if ctype == "aitt":
        raise ValueError(
            f"{el.name}: connect-type=AITT is the Tizen-only transport "
            "this framework drops — use TCP or HYBRID")
    if not (el.dest_port in (None, "", 0) and el.dest_host in (None, "")):
        host = str(el.dest_host or "127.0.0.1")
        port = el.dest_port
        if ctype == "hybrid":
            # dest-* is the MQTT broker; its well-known port is the
            # default when only dest-host was given
            el.mqtt_host = host
            el.mqtt_port = int(port) if port not in (None, "", 0) else 1883
        else:
            if port in (None, "", 0):
                # a silent port-0 connect would be an opaque OSError on
                # the wrong machine (same guard as tensor_query_client)
                raise ValueError(f"{el.name}: dest-host={host!r} needs "
                                 "dest-port")
            el.host = host
            el.port = int(port)
    return ctype


@register_element
class EdgeSink(Element):
    """Publish the stream to a broker topic (edge_sink role).

    ``connect-type`` mirrors libnnstreamer-edge's transports
    (tensor_query_common.h:33-34): ``tcp`` (default) talks straight to
    the TCP broker; ``hybrid`` additionally advertises the broker's
    ``host:port`` as a RETAINED MQTT message on ``nns/edge/<topic>`` so
    subscribers discover the data channel via the MQTT broker and then
    stream over TCP — the reference's MQTT-hybrid control/data split
    (Documentation/component-description.md:158-163)."""

    FACTORY = "edge_sink"
    PROPERTIES = {
        "host": ("127.0.0.1", "broker host"),
        "port": (0, "broker port"),
        "topic": ("default", ""),
        "connect-type": ("tcp", "tcp | hybrid (MQTT discovery + TCP data)"),
        "mqtt-host": ("127.0.0.1", "MQTT broker host (connect-type=hybrid)"),
        "mqtt-port": (1883, "MQTT broker port (connect-type=hybrid)"),
        "advertise-host": (None, "externally reachable address published "
                                 "in the hybrid discovery record (default: "
                                 "the host property — loopback only "
                                 "reaches same-host subscribers)"),
        "dest-host": (None, "reference addressing: the TCP broker "
                            "(connect-type=TCP) or the MQTT broker "
                            "(HYBRID) — resolves onto host/mqtt-host "
                            "at start"),
        "dest-port": (None, "reference addressing: broker port"),
        "ntp-host": (None, "NTP server(s) for epoch alignment, comma-sep "
                           "(default: local wall clock)"),
        "retry": (None, "reconnect policy spec 'attempts=4,base=0.05,"
                        "cap=0.5,…' applied when a publish send fails "
                        "(broker restart survival)"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def start(self):
        from ..utils.ntp import stream_origin_epoch_us

        self._ctype = _resolve_reference_dest(self)
        self._retry = _edge_retry(self.retry)
        if self._ctype == "hybrid" and int(self.port or 0) == 0:
            # verbatim reference HYBRID sink lines configure ONLY the
            # MQTT broker (dest-*): there the sink itself is the data
            # endpoint, so with no data broker configured start an
            # in-process one and advertise it — subscribers discover
            # whatever address the record carries either way
            broker = get_broker()
            self.host, self.port = broker.host, broker.port
        self._caps_str: Optional[str] = None
        self._caps_sent = False
        self._dial_broker()
        # stream-origin epoch: wall clock (NTP-aligned when ntp-host set) at
        # start, when running-time 0 ≈ now — the reference mqttsink's
        # base_time_epoch (mqttsink.c, synchronization-in-mqtt-elements.md)
        self._base_epoch_us = stream_origin_epoch_us(self.ntp_host, self.name)
        self._mqtt = None
        if self._ctype == "hybrid":
            from .mqtt import MqttClient

            self._mqtt = MqttClient(str(self.mqtt_host),
                                    int(self.mqtt_port),
                                    f"nns-edge-sink-{self.name}",
                                    publish_only=True)
            adv = str(self.advertise_host or self.host)
            self._mqtt.publish(
                f"nns/edge/{self.topic}",
                f"{adv}:{int(self.port)}".encode(), retain=True)

    def stop(self):
        if self._mqtt is not None:
            try:
                # clear the retained discovery record so late subscribers
                # get the clean "no record" error, not a dead address
                self._mqtt.publish(f"nns/edge/{self.topic}", b"",
                                   retain=True)
            except OSError:
                pass
            self._mqtt.close()
        try:
            send_msg(self._sock, Message(T_BYE))
            self._sock.close()
        except OSError:
            pass

    def _dial_broker(self) -> None:
        """(Re)connect to the broker and re-announce the pub role (+caps
        when already negotiated, restoring the retained record a
        restarted broker lost)."""
        old = getattr(self, "_sock", None)
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        self._sock = checked_connect(
            (str(self.host), int(self.port)), timeout=10)
        # publisher sockets only SEND: keep a bounded (long) send timeout
        # so a wedged broker/subscriber surfaces as a pipeline error
        # instead of hanging chain() forever (a timed-out partial send
        # would desync the stream, but the error tears the connection
        # down anyway)
        self._sock.settimeout(30.0)
        if self._caps_str is not None:
            send_msg(self._sock, Message(T_HELLO, payload=(
                f"pub:{self.topic}|{self._caps_str}").encode()))
            self._caps_sent = True
        elif self._caps_sent:
            send_msg(self._sock, Message(
                T_HELLO, payload=f"pub:{self.topic}".encode()))

    def _send_resilient(self, msg: Message) -> None:
        self._send_resilient_fn(lambda sock: send_msg(sock, msg))

    def _send_resilient_fn(self, send_fn) -> None:
        """Send via ``send_fn(sock)``, reconnecting with backoff on
        failure (satellite fix: a publisher socket used to die
        permanently on the first send error — one broker restart killed
        the pipeline)."""
        try:
            send_fn(self._sock)
            return
        except OSError:
            STATS.incr("edge.send_failures")

        def _redial_and_send():
            self._dial_broker()
            send_fn(self._sock)
            STATS.incr("edge.pub_reconnects")

        try:
            self._retry.run(_redial_and_send,
                            retry_on=(OSError, ConnectionError),
                            counter="edge.reconnect")
        except RetryExhausted as exc:
            raise ConnectionError(
                f"{self.name}: cannot republish to broker "
                f"{self.host}:{self.port}: {exc.__cause__!r}") from exc

    def set_caps(self, pad, caps):
        self._caps_str = str(caps)
        self._send_resilient(Message(T_HELLO, payload=(
            f"pub:{self.topic}|{caps}").encode()))
        self._caps_sent = True

    def chain(self, pad, buf):
        if not self._caps_sent:
            self._send_resilient(Message(
                T_HELLO, payload=f"pub:{self.topic}".encode()))
            self._caps_sent = True
        # trace propagation (obs/span.py): the publisher's trace context
        # rides the rev-4 header so subscriber-side spans join the trace
        from ..obs.span import TraceContext

        ctx = buf.extra.get("nns_trace") or TraceContext()
        # scatter-gather publish: tensor views go straight to sendmsg
        self._send_resilient_fn(
            lambda sock: send_tensors(sock, T_DATA, buf,
                                      pts=buf.pts or 0,
                                      epoch_us=self._base_epoch_us,
                                      trace_id=ctx.trace_id,
                                      span_id=ctx.span_id,
                                      origin_us=ctx.origin_us))
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self.post_eos_reached()


@register_element
class EdgeSrc(Source):
    """Subscribe to a broker topic (edge_src role).

    ``connect-type=hybrid`` discovers the TCP broker's address from the
    RETAINED MQTT record a hybrid edge_sink published on
    ``nns/edge/<topic>`` — the subscriber then needs only the MQTT
    broker's address (the reference's MQTT-hybrid discovery)."""

    FACTORY = "edge_src"
    PROPERTIES = {
        "host": ("127.0.0.1", "broker host"),
        "port": (0, "broker port"),
        "topic": ("default", ""),
        "connect-type": ("tcp", "tcp | hybrid (MQTT discovery + TCP data)"),
        "mqtt-host": ("127.0.0.1", "MQTT broker host (connect-type=hybrid)"),
        "mqtt-port": (1883, "MQTT broker port (connect-type=hybrid)"),
        "dest-host": (None, "reference addressing: the TCP broker "
                            "(connect-type=TCP) or the MQTT broker "
                            "(HYBRID)"),
        "dest-port": (None, "reference addressing: broker port"),
        "caps": (None, "override caps (else retained topic caps)"),
        "num-buffers": (-1, "stop after N buffers, -1 unlimited"),
        "sync-pts": (False, "re-base incoming PTS onto this host's clock "
                            "using the sender's embedded epoch"),
        "ntp-host": (None, "NTP server(s) for epoch alignment, comma-sep"),
        "retry": (None, "reconnect policy spec 'attempts=4,base=0.05,"
                        "cap=0.5,…' applied when the broker link drops "
                        "(resubscribe after broker restart)"),
    }

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def _discover_hybrid(self) -> None:
        """Resolve host/port from the retained MQTT discovery record
        (bounded wait mirroring the TCP path's 10 s connect timeout)."""
        from .mqtt import fetch_retained_record

        record = fetch_retained_record(
            str(self.mqtt_host), int(self.mqtt_port),
            f"nns/edge/{self.topic}", 10.0, f"nns-edge-src-{self.name}")
        if not record:
            raise ValueError(
                f"{self.name}: no retained discovery record on "
                f"nns/edge/{self.topic}")
        host, sep, port = record.decode().rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(f"{self.name}: malformed discovery record "
                             f"{record!r} (want host:port)")
        self.host, self.port = host, int(port)

    def start(self):
        from ..utils.ntp import stream_origin_epoch_us

        self._ctype = _resolve_reference_dest(self)
        self._retry = _edge_retry(self.retry)
        self._closing = False
        # own stream-origin epoch, for re-basing sender PTS (the receiver
        # half of the reference's NTP-based mqtt timestamp alignment)
        self._base_epoch_us = stream_origin_epoch_us(self.ntp_host, self.name)
        if self._ctype == "hybrid":
            self._discover_hybrid()
        self._sock = None
        self._subscribe()
        # paced by the broker's TCP stream and drained every create();
        # bounding needs a stop-cancellable put in the reader thread —
        # the serving-plane admission story (query/overload.py) covers
        # the query path, pub/sub keeps QoS-0 semantics for now
        # nnslint: allow(unbounded-queue)
        self._fifo: _queue.Queue = _queue.Queue()
        self._retained_caps: Optional[str] = None
        self._caps_evt = threading.Event()
        self._count = 0
        threading.Thread(target=self._read_loop, daemon=True,
                         name=f"edge-src:{self.name}").start()

    class _Closing(Exception):
        """Teardown raced a resubscribe: abort the retry loop (not an
        OSError, so RetryPolicy.run doesn't keep dialing)."""

    def _subscribe(self) -> None:
        """Dial the broker and announce the sub role (used at start and
        after a broker restart — the retained topic caps are redelivered
        on the new link, so a resubscribed source keeps streaming)."""
        if self._closing:
            raise EdgeSrc._Closing()
        old = self._sock
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        sock = checked_connect(
            (str(self.host), int(self.port)), timeout=10)
        # the connect timeout must NOT persist as an idle-read timeout: a
        # subscriber legitimately sits idle until the first publish (e.g.
        # while a downstream model compiles), and _recv_exact would treat
        # the timeout as EOF, silently killing the subscription — the
        # round-2 edge-bench deadline failure
        sock.settimeout(None)
        send_msg(sock, Message(T_HELLO,
                               payload=f"sub:{self.topic}".encode()))
        self._sock = sock
        if self._closing:
            # stop() may have closed the OLD socket while we dialed; it
            # must not leave this fresh one (and a reader blocked on it)
            shutdown_close(sock)
            raise EdgeSrc._Closing()

    def stop(self):
        self._closing = True
        # shutdown-then-close wakes the read loop blocked in recv
        # (protocol.py) so teardown doesn't leak a subscriber thread
        shutdown_close(self._sock)
        super()._halt()

    def _read_loop(self) -> None:
        pool = default_pool()
        while True:
            try:
                msg = recv_msg(self._sock, pool=pool)
            except ValueError as e:   # bad magic / CRC: stream corrupt
                from ..utils.log import logger

                logger.error("edge src %s: corrupt stream: %s",
                             self.name, e)
                msg = None
            if msg is None:
                # link dropped: resubscribe with backoff unless this is
                # element teardown (broker-restart survival; the broker
                # pushes the retained caps again once a publisher
                # re-announces them)
                if not self._closing and not self._halted.is_set():
                    try:
                        self._retry.run(self._subscribe,
                                        retry_on=(OSError,
                                                  ConnectionError),
                                        counter="edge.resubscribe")
                        STATS.incr("edge.resubscribes")
                        continue
                    except EdgeSrc._Closing:
                        pass   # teardown raced the redial
                    except RetryExhausted as e:
                        from ..utils.log import logger

                        logger.error("edge src %s: broker gone, giving "
                                     "up: %s", self.name, e)
                self._fifo.put(None)
                return
            if msg.type == T_HELLO:
                if msg.payload:
                    self._retained_caps = msg.payload.decode()
                    self._caps_evt.set()
            elif msg.type == T_DATA:
                pts = msg.pts
                if self.sync_pts and msg.epoch_us:
                    # sender running-time → this host's running time:
                    # shift by the epoch difference (µs → ns)
                    pts = msg.pts + (msg.epoch_us - self._base_epoch_us) * 1000
                buf = TensorBuffer(tensors=decode_tensors(msg.payload),
                                   pts=pts, lease=msg.lease)
                if msg.trace_id:
                    from ..obs.span import TraceContext

                    buf.extra["nns_trace"] = TraceContext(
                        msg.trace_id, msg.span_id, msg.origin_us)
                self._fifo.put(buf)

    def negotiate(self) -> Caps:
        if self.caps:
            c = self.caps
            return Caps.from_string(c) if isinstance(c, str) else c
        self._caps_evt.wait(timeout=10)
        if self._retained_caps:
            return Caps.from_string(self._retained_caps)
        raise ValueError(f"{self.name}: no caps known for topic "
                         f"{self.topic!r}; set the caps property")

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.num_buffers)
        if n >= 0 and self._count >= n:
            return None
        while not self._halted.is_set():
            try:
                item = self._fifo.get(timeout=0.1)
            except _queue.Empty:
                continue
            if item is not None:
                self._count += 1
            return item
        return None


# the reference registers these factories WITHOUT the underscore
# (gst/edge/edge_elements.c) — verbatim reference launch lines use
# `edgesink`/`edgesrc`
register_element_alias("edgesink", EdgeSink)
register_element_alias("edgesrc", EdgeSrc)
