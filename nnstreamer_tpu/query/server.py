"""Tensor query server: serve pipeline inference to remote clients.

Parity with the reference server trio (SURVEY.md §2.7):
- gst/nnstreamer/tensor_query/tensor_query_serversrc.c (receive → queue →
  push into the serving pipeline)
- tensor_query_serversink.c (send answers matched by client id meta)
- tensor_query_server.c (shared server-data table pairing src/sink by id)

The transport thread owns the sockets; client identity rides in
``buf.extra["query_client_id"]`` (the role of GstMeta in
gst/nnstreamer/tensor_meta.c).
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Dict, Optional

from ..analysis.sanitizer import make_condition, make_lock
from ..obs.clock import wall_us
from ..obs.span import TraceContext
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer, XBatchMeta, default_pool
from ..tensor.caps_util import tensors_template_caps
from ..utils.conf import parse_bool
from .overload import (DEFAULT_QOS, QOS_CLASSES, AdmissionController,
                       TokenBucket, bucket_budget, qos_of_class)
from .protocol import (Message, T_BYE, T_DATA, T_HELLO, T_METRICS,
                       T_PING, T_PONG, T_REPLY, T_SHED, T_TRACE,
                       decode_tensors, parse_hello_tokens, recv_msg,
                       send_msg, send_tensors, shutdown_close)

#: default bound on the server's incoming frame queue (frames, not
#: bytes): deep enough that bursty-but-sustainable traffic never sheds,
#: shallow enough that queued latency stays bounded (256 frames at the
#: measured ~2 ms/query loopback service time is ~0.5 s of backlog)
DEFAULT_QUEUE_DEPTH = 256
#: default per-connection socket send timeout: a client that stops
#: draining replies for this long is a zombie and gets evicted, instead
#: of wedging the serving pipeline thread inside reply()
DEFAULT_SEND_TIMEOUT = 5.0


class QueryServer:
    """Accepts clients, queues incoming frames, routes replies by client id.

    The shared table (reference tensor_query_server.c:76-238) pairs the
    serversrc and serversink elements of one serving pipeline.

    Overload safety (query/overload.py): ``incoming`` is BOUNDED
    (``queue_depth`` frames) and every DATA frame passes admission
    control before its tensors pin a pooled slab — a refused request is
    answered with an explicit ``T_SHED`` carrying a retry-after hint,
    chosen by QoS class (bronze sheds first, gold last; per-connection
    class negotiated in the T_HELLO handshake).  ``drain(deadline)``
    stops admitting, finishes in-flight replies, then closes — the
    server half of the pipeline ``draining`` lifecycle state.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH,
                 admission: Optional[AdmissionController] = None,
                 shed: bool = True,
                 send_timeout: float = DEFAULT_SEND_TIMEOUT):
        self.host = host
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self.queue_depth = max(1, int(queue_depth))
        self.incoming: _queue.Queue = _queue.Queue(maxsize=self.queue_depth)
        #: admit-or-shed decider; ``shed=False`` disables shedding
        #: entirely (overload degrades to per-connection backpressure
        #: on the bounded queue — the pre-overload-layer behavior,
        #: minus the unbounded memory growth)
        self.admission = (admission if admission is not None
                          else AdmissionController()) if shed else None
        self.send_timeout = float(send_timeout)
        self._clients: Dict[int, socket.socket] = {}
        # per-client send locks: the reader thread's handshake/pong
        # replies must not interleave with a partially-written T_REPLY
        # from the pipeline thread (mirror of the client's _send_lock)
        self._send_locks: Dict[int, threading.Lock] = {}
        self._qos: Dict[int, str] = {}   # client id -> negotiated class
        self._caps_str: Optional[str] = None
        self._next_id = 1
        #: serving pipeline's tracer (set by the serversink element);
        #: when it records spans, replies piggyback them as T_TRACE so
        #: the client merges both processes into one timeline
        self.obs_tracer = None
        #: telemetry-federation collector (obs/federation.py): attach
        #: one and every connection doubles as a metrics drain —
        #: T_METRICS pushes from worker processes already connected to
        #: this front-end merge into the federated view without a
        #: second wire.  Unattached (the default), pushes are ignored.
        self.collector = None
        self._span_cursors: Dict[int, int] = {}   # client id -> ring pos
        self._lock = make_lock("query.registry")
        self._stop = threading.Event()
        self._draining = threading.Event()
        #: admitted-minus-replied frames; drain() waits for zero
        self._inflight = 0
        self._drain_cv = make_condition("query.registry")
        self.peak_depth = 0
        # scrape-time gauges for the soak harness: connected-client
        # count / queue depth / shed rate are lazy callables (zero
        # per-frame cost); admit/shed counters are one inc per decision
        from ..obs.metrics import REGISTRY

        self._m_clients = REGISTRY.gauge(
            "nns_query_server_clients", fn=lambda: len(self._clients),
            port=str(self.port))
        self._m_accepted = REGISTRY.counter(
            "nns_query_server_accepted_total", port=str(self.port))
        self._m_depth = REGISTRY.gauge(
            "nns_query_server_queue_depth",
            fn=self.incoming.qsize, port=str(self.port))
        self._m_peak = REGISTRY.gauge(
            "nns_query_server_queue_peak",
            fn=lambda: self.peak_depth, port=str(self.port))
        self._m_admitted = {
            c: REGISTRY.counter("nns_query_server_admitted_total",
                                port=str(self.port), qos=c)
            for c in QOS_CLASSES}
        self._m_shed = {
            c: REGISTRY.counter("nns_query_server_shed_total",
                                port=str(self.port), qos=c)
            for c in QOS_CLASSES}
        self._m_shed_rate = REGISTRY.gauge(
            "nns_query_server_shed_rate", fn=self._shed_rate,
            port=str(self.port))
        self._m_evicted = REGISTRY.counter(
            "nns_query_server_evicted_total", port=str(self.port))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="query-accept")
        self._accept_thread.start()

    def _shed_rate(self) -> float:
        shed = sum(c.value for c in self._m_shed.values())
        admitted = sum(c.value for c in self._m_admitted.values())
        return shed / max(1, shed + admitted)

    def counters(self) -> Dict[str, Dict[str, int]]:
        """Point-in-time admit/shed counts by QoS class (test/verdict
        surface; the live metrics ride the registry)."""
        return {"admitted": {c: m.value
                             for c, m in self._m_admitted.items()},
                "shed": {c: m.value for c, m in self._m_shed.items()}}

    def set_caps_string(self, caps: str) -> None:
        self._caps_str = caps

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # bound EVERY per-connection send path: a client that stops
            # draining its socket can only stall a send for
            # send_timeout before it is evicted, instead of wedging the
            # pipeline thread inside reply() forever.  The same timeout
            # applies to the reader's recv — protocol.recv_msg treats
            # an idle timeout as retryable, so quiet clients survive.
            if self.send_timeout > 0:
                conn.settimeout(self.send_timeout)
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                self._clients[cid] = conn
                self._send_locks[cid] = make_lock("query.send")
            self._m_accepted.inc()
            threading.Thread(target=self._client_loop, args=(cid, conn),
                             daemon=True, name=f"query-client-{cid}").start()

    def _admit_frame(self, cid: int, msg: Message) -> Optional[float]:
        """Admission decision for one DATA frame: ``None`` admits, a
        float sheds with that retry-after hint (seconds).  Header-only:
        runs BEFORE the payload is decoded into tensors, so a shed
        request's slab goes straight back to the pool."""
        if self.admission is None:
            return None
        qos = self._qos.get(cid, DEFAULT_QOS)
        return self.admission.admit(qos, self.incoming.qsize(),
                                    self.queue_depth)

    def _send_shed(self, conn, slock, cid: int, seq: int,
                   retry_after_s: float) -> None:
        qos = self._qos.get(cid, DEFAULT_QOS)
        self._m_shed[qos].inc()
        with slock:
            send_msg(conn, Message(
                T_SHED, client_id=cid, seq=seq, epoch_us=wall_us(),
                payload=str(int(retry_after_s * 1000)).encode()))

    def _client_loop(self, cid: int, conn: socket.socket) -> None:
        # snapshot: stop() clears the dict concurrently, and a KeyError
        # here would escape the except-OSError below
        slock = self._send_locks.get(cid) or make_lock("query.send")
        pool = default_pool()
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn, pool=pool)
                except TimeoutError:   # idle socket on a bounded-send
                    continue           # connection: keep listening
                except ValueError:   # bad magic / CRC: drop the connection
                    break
                if msg is None or msg.type == T_BYE:
                    break
                if msg.type == T_HELLO:
                    # capability handshake: record the client's QoS
                    # declaration (``qos=<class>`` token —
                    # query/overload.py; the payload is the ``;``-token
                    # grammar so fleet clients may also carry a model
                    # identity), reply with server caps string
                    tokens = parse_hello_tokens(msg.payload)
                    if "qos" in tokens:
                        qos = qos_of_class(tokens["qos"])
                        if qos is not None:
                            with self._lock:
                                self._qos[cid] = qos
                    with slock:
                        send_msg(conn, Message(T_HELLO, client_id=cid,
                                               payload=(self._caps_str
                                                        or "").encode()))
                    continue
                if msg.type == T_PING:
                    # liveness heartbeat: echo seq+payload immediately,
                    # out of band with DATA/REPLY (query/resilience.py).
                    # The pong also stamps this host's wall clock: a
                    # ping round trip has near-zero service time, so it
                    # is the UNBIASED clock-offset sample (obs/clock.py)
                    # — a reply stamp rides on top of model latency.
                    with slock:
                        send_msg(conn, Message(T_PONG, client_id=cid,
                                               seq=msg.seq,
                                               epoch_us=wall_us(),
                                               payload=msg.payload))
                    continue
                if msg.type == T_METRICS:
                    # telemetry piggyback (obs/federation.py): a worker
                    # pushing its registry on the data wire.  One attr
                    # read per push on unattached servers; the payload
                    # is JSON, never tensors, so no slab is pinned.
                    collector = self.collector
                    if collector is not None:
                        collector.ingest(bytes(msg.payload or b""))
                    continue
                if msg.type == T_DATA:
                    # admission BEFORE tensor decode: a shed frame's
                    # pooled payload slab releases immediately instead
                    # of pinning memory through the serving pipeline
                    retry_after = self._admit_frame(cid, msg)
                    if retry_after is not None:
                        if msg.lease is not None:
                            msg.payload = b""
                            msg.lease.release()
                        self._send_shed(conn, slock, cid, msg.seq,
                                        retry_after)
                        continue
                    buf = TensorBuffer(tensors=decode_tensors(msg.payload),
                                       pts=msg.pts, lease=msg.lease)
                    buf.extra["query_client_id"] = cid
                    buf.extra["query_seq"] = msg.seq
                    buf.extra["nns_class"] = qos = self._qos.get(
                        cid, DEFAULT_QOS)
                    if msg.trace_id:
                        # restore the client's trace context: spans this
                        # buffer produces in the serving pipeline record
                        # under the client's trace id (obs/span.py)
                        buf.extra["nns_trace"] = TraceContext(
                            msg.trace_id, msg.span_id, msg.origin_us)
                    self._enqueue(conn, slock, cid, qos, buf)
        except OSError:
            pass   # link reset under us (recv, or a handshake/pong send)
        finally:
            with self._lock:
                self._clients.pop(cid, None)
                self._send_locks.pop(cid, None)
                self._qos.pop(cid, None)
                # client ids are never reused: an unreaped cursor per
                # connection ever made is a slow leak on a long server
                self._span_cursors.pop(cid, None)
            conn.close()

    def _dec_inflight(self) -> None:
        with self._drain_cv:
            if self._inflight > 0:
                self._inflight -= 1
                if self._inflight == 0:
                    self._drain_cv.notify_all()

    def _enqueue(self, conn, slock, cid: int, qos: str,
                 buf: TensorBuffer) -> None:
        """Admit ``buf`` into the bounded queue.  With shedding enabled
        a full queue sheds (the queue bound is the hard watermark the
        policy's soft watermarks sit under); without it, the put blocks
        — per-connection backpressure, woken by stop().

        The in-flight count is raised BEFORE the put: the pipeline
        thread can dequeue and reply the instant the frame lands, and
        a decrement racing ahead of the increment would leave a
        permanent +1 skew that makes drain() time out forever."""
        tracer = self.obs_tracer
        if tracer is not None and tracer.ring is not None:
            # wait-state attribution (obs/attrib.py): arrival stamp so
            # the serversrc can annotate this frame's admission-wait —
            # the time it sat in the bounded queue before the serving
            # pipeline picked it up.  Untraced servers pay one attr
            # read + None test per frame.
            from ..obs.clock import mono_ns

            buf.extra["nns_enq_ns"] = mono_ns()
        with self._drain_cv:
            self._inflight += 1
        while not self._stop.is_set():
            try:
                self.incoming.put(buf, timeout=0.25)
            except _queue.Full:
                if self.admission is not None:
                    self._dec_inflight()   # refused after all
                    buf.lease = None   # buffer dies here: drop its slab
                    self._send_shed(conn, slock, cid,
                                    buf.extra.get("query_seq", 0),
                                    retry_after_s=0.25)
                    return
                continue
            self._m_admitted[qos].inc()
            depth = self.incoming.qsize()
            if depth > self.peak_depth:
                self.peak_depth = depth
            return
        self._dec_inflight()           # server stopped before the put

    def _trace_piggyback(self, cid: int, ctx: TraceContext
                         ) -> Optional[Message]:
        """T_TRACE message carrying this pipeline's new spans for the
        client's trace, or None when there is nothing to send (no
        span-recording tracer attached, or no new spans)."""
        tracer = self.obs_tracer
        if tracer is None or getattr(tracer, "ring", None) is None \
                or not ctx.trace_id:
            return None
        import json as _json

        with self._lock:
            cursor = self._span_cursors.get(cid, 0)
        payload, cursor = tracer.publish_spans(cursor,
                                               trace_id=ctx.trace_id)
        with self._lock:
            self._span_cursors[cid] = cursor
        if not payload["spans"]:
            return None
        return Message(T_TRACE, client_id=cid,
                       trace_id=ctx.trace_id,
                       epoch_us=wall_us(),
                       payload=_json.dumps(payload).encode())

    def reply(self, buf: TensorBuffer) -> bool:
        try:
            return self._reply(buf)
        finally:
            # in-flight accounting runs on EVERY outcome — including a
            # reply for a client that disconnected mid-request — so
            # drain() converges exactly when the last admitted frame
            # has been answered (or become unanswerable).  STREAMING
            # answers (the llm tier's per-token frames) mark every
            # frame but the last with ``extra["nns_more"]``: one
            # admitted request stays ONE in-flight unit until its final
            # frame, so drain() waits for whole token streams, not just
            # their first token.
            if not buf.extra.get("nns_more"):
                self._dec_inflight()

    def shed_frame(self, extra: Dict, retry_after_s: float) -> bool:
        """Explicit ``T_SHED`` for an ALREADY-ADMITTED frame that a
        downstream serving stage refused — the llm tier's KV-cache slot
        admission (``nnstreamer_tpu/llm``): queue-depth admission at the
        wire cannot see slot exhaustion, so the element answers the
        frame's client here with a retry-after hint instead of holding
        the request as unbounded memory.  Settles the frame's in-flight
        unit (a shed IS its final answer); returns False when the
        client is already gone (its accounting still settles)."""
        cid = extra.get("query_client_id")
        seq = extra.get("query_seq", 0)
        try:
            with self._lock:
                conn = self._clients.get(cid)
                slock = self._send_locks.get(cid)
            if conn is None:
                return False
            if slock is None:
                slock = make_lock("query.send")   # teardown race
            self._send_shed(conn, slock, cid, seq, retry_after_s)
            return True
        except OSError:
            return False
        finally:
            self._dec_inflight()

    def client_connected(self, cid) -> bool:
        """Is this client id still connected?  The llm tier's session
        pruner polls it so a disconnected client's cache slot reclaims
        promptly instead of decoding tokens nobody will read."""
        with self._lock:
            return cid in self._clients

    def _reply(self, buf: TensorBuffer) -> bool:
        cid = buf.extra.get("query_client_id")
        with self._lock:
            conn = self._clients.get(cid)
            slock = self._send_locks.get(cid)
        if conn is None:
            return False
        seq = buf.extra.get("query_seq", 0)
        ctx = buf.extra.get("nns_trace") or TraceContext()
        trace_msg = self._trace_piggyback(cid, ctx)
        try:
            if slock is None:
                slock = make_lock("query.send")   # teardown race: one-shot
            with slock:
                # reply stamps: echo the trace context, carry this
                # host's wall clock so the client estimates the offset
                # (obs/clock.py) from the very frames it already sends
                send_tensors(conn, T_REPLY, buf, client_id=cid,
                             seq=seq, pts=buf.pts or 0,
                             epoch_us=wall_us(),
                             trace_id=ctx.trace_id, span_id=ctx.span_id,
                             origin_us=ctx.origin_us)
                if trace_msg is not None:
                    send_msg(conn, trace_msg)
            return True
        except socket.timeout:
            # the bounded send path fired: this client stopped draining
            # its socket.  Evict it — a zombie peer must cost one send
            # timeout, not one timeout per reply forever.
            self._m_evicted.inc()
            with self._lock:
                self._clients.pop(cid, None)
            shutdown_close(conn)
            return False
        except OSError:
            return False

    def drain(self, deadline: float = 5.0) -> bool:
        """Graceful drain: stop admitting (every new DATA frame sheds
        with a retry-after sized past the drain), let in-flight frames
        finish their replies, then close.  Returns True when the last
        in-flight reply completed within ``deadline`` seconds, False on
        a deadline cut (remaining frames are dropped by close()).

        Wired to the pipeline ``draining`` lifecycle state: the
        /healthz endpoint answers 503 while this runs, so load
        balancers route away while existing requests complete.
        """
        self._draining.set()
        if self.admission is None:
            # drain must stop admitting even on a shed=False server:
            # install a controller whose only act is the drain-mode
            # shed-everything answer
            self.admission = AdmissionController()
        self.admission.start_drain(deadline)
        with self._drain_cv:
            ok = self._drain_cv.wait_for(
                lambda: self._inflight <= 0, timeout=max(0.0, deadline))
        self.close()
        return bool(ok)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def close(self) -> None:
        self._stop.set()
        from ..obs.metrics import REGISTRY

        for g in (self._m_clients, self._m_depth, self._m_peak,
                  self._m_shed_rate):
            REGISTRY.unregister(g)
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._clients.values())
            self._clients.clear()
            self._send_locks.clear()
            self._qos.clear()
        for conn in conns:
            # shutdown-then-close: a plain close of a socket another
            # thread is blocked reading sends no FIN (protocol.py)
            shutdown_close(conn)


#: server table: id → QueryServer (pairs serversrc/serversink)
_SERVERS: Dict[int, QueryServer] = {}
_SERVERS_LOCK = make_lock("leaf")


def get_server(server_id: int, host: str = "127.0.0.1",
               port: int = 0,
               queue_depth: Optional[int] = None,
               shed: Optional[bool] = None,
               capacity_rps: float = 0.0,
               send_timeout: Optional[float] = None) -> QueryServer:
    """Server-table lookup; overload-protection kwargs apply only when
    this call CREATES the server (the serversink's bare lookup must not
    reconfigure the serversrc's server)."""
    with _SERVERS_LOCK:
        if server_id not in _SERVERS:
            admission = None
            if capacity_rps and float(capacity_rps) > 0:
                admission = AdmissionController(
                    bucket=TokenBucket(float(capacity_rps)))
            _SERVERS[server_id] = QueryServer(
                host, port,
                queue_depth=(DEFAULT_QUEUE_DEPTH if queue_depth is None
                             else int(queue_depth)),
                admission=admission,
                shed=(True if shed is None else bool(shed)),
                send_timeout=(DEFAULT_SEND_TIMEOUT if send_timeout is None
                              else float(send_timeout)))
        return _SERVERS[server_id]


def peek_server(server_id: int) -> Optional[QueryServer]:
    """Server-table read WITHOUT creation: consumers that only want an
    existing server's state (the llm element's disconnect pruner) must
    not conjure a default-configured server into the table."""
    with _SERVERS_LOCK:
        return _SERVERS.get(server_id)


def shutdown_server(server_id: int) -> None:
    with _SERVERS_LOCK:
        srv = _SERVERS.pop(server_id, None)
    if srv is not None:
        srv.close()


@register_element
class TensorQueryServerSrc(Source):
    """Receives client frames and pushes them into the serving pipeline."""

    FACTORY = "tensor_query_serversrc"
    PROPERTIES = {
        "host": ("127.0.0.1", ""),
        "port": (0, "0 = ephemeral"),
        "id": (0, "server table id"),
        "caps": (None, "caps announced for received tensors"),
        "connect-type": ("tcp", "TCP | HYBRID (reference nicks; hybrid "
                                "advertises this server's address as a "
                                "retained MQTT record under the topic)"),
        "dest-host": ("127.0.0.1", "hybrid: MQTT broker host"),
        "dest-port": (1883, "hybrid: MQTT broker port"),
        "topic": (None, "hybrid: discovery topic"),
        "advertise-host": (None, "address to advertise in the hybrid "
                                 "record (default: host — set it when "
                                 "bound to 0.0.0.0, which is not a "
                                 "reachable address for remote "
                                 "clients)"),
        "queue-depth": (256, "bound on the incoming frame queue; the "
                             "hard watermark the shed policy's soft "
                             "watermarks sit under"),
        "shed": (True, "admission control: refused frames get explicit "
                       "T_SHED answers with retry-after, QoS-tiered "
                       "(bronze first, gold last — query/overload.py); "
                       "false = pure per-connection backpressure on "
                       "the bounded queue"),
        "capacity-rps": (0.0, "token-bucket admission rate in "
                              "requests/s across all clients "
                              "(0 = depth/latency watermarks only)"),
        "send-timeout": (5.0, "per-connection socket send bound in "
                              "seconds; a client that stops draining "
                              "replies for this long is evicted "
                              "(0 = unbounded sends)"),
        "batch": (1, "cross-stream continuous batching: coalesce up to "
                     "N admitted frames from ALL connected clients into "
                     "one stacked buffer that traverses the serving "
                     "pipeline — and its fused segment plan — as a "
                     "single dispatch, answered per client by the "
                     "paired serversink.  The bucket never waits for "
                     "more frames than the connected-client population "
                     "can have outstanding (fill target = min(batch, "
                     "clients)), so a lone client pays ~zero batching "
                     "tax.  1 = per-frame serving (default)"),
        "batch-timeout-ms": (0.0, "extra wait to FILL a cross-stream "
                                  "bucket once it has a frame, scaled "
                                  "per QoS class (gold waits 1/4 of "
                                  "this, silver 1/2, bronze all — a "
                                  "gold frame never waits out a "
                                  "bronze-opened bucket's window; "
                                  "query/overload.py bucket_budget).  "
                                  "0 = greedy continuous batching: "
                                  "dispatch whatever is queued the "
                                  "moment the previous bucket clears "
                                  "(the service time of bucket k is "
                                  "bucket k+1's natural collect "
                                  "window)"),
    }

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def static_check(self):
        """Pre-play verifier hook: surface the batching decisions
        ``start()`` would make silently (mirrors tensor_filter's
        checks)."""
        out = []
        try:
            # mirrors start(): batch=0 and unset both serve unbatched
            # nnslint: allow(falsy-zero-default)
            batch = int(self.batch or 1)
        except (TypeError, ValueError):
            out.append(("error", f"{self.name}: batch={self.batch!r} is "
                                 "not an integer"))
            batch = 1
        try:
            timeout = float(self.batch_timeout_ms or 0)
        except (TypeError, ValueError):
            out.append(("error", f"{self.name}: batch-timeout-ms="
                                 f"{self.batch_timeout_ms!r} is not a "
                                 "number"))
            timeout = 0.0
        if batch < 1:
            out.append(("warning", f"{self.name}: batch={batch} is "
                                   "clamped to 1 at start"))
        if timeout > 0 and batch <= 1:
            out.append(("warning",
                        f"{self.name}: batch-timeout-ms needs "
                        "cross-stream batching (batch>1); ignored"))
        return out

    def start(self):
        self.server = get_server(int(self.id), str(self.host),
                                 int(self.port),
                                 queue_depth=int(self.queue_depth),
                                 shed=parse_bool(self.shed),
                                 capacity_rps=float(self.capacity_rps),
                                 send_timeout=float(self.send_timeout))
        if self.caps:
            self.server.set_caps_string(str(self.caps))
        # cross-stream continuous batching (the one-TPU-per-client-
        # population lever): a per-model bucket — one per server table
        # id, which pairs exactly one serving pipeline / negotiated
        # caps / model — coalescing admitted frames ACROSS client
        # connections, reusing tensor_filter's bucket/dispatch core
        # batch=0 and unset both clamp to 1 under max()
        # nnslint: allow(falsy-zero-default)
        self._xbatch = max(1, int(self.batch or 1))
        self._xb_timeout = max(0.0, float(self.batch_timeout_ms or 0)) / 1e3
        self._xb_hold = None          # shape-mismatch holdover frame
        self._xb_last_fill = 0.0
        self._xb_gauges = []
        if self._xbatch > 1:
            from ..elements.filter_elem import CrossStreamBatcher
            from ..obs.metrics import REGISTRY

            self._xb_bucket = CrossStreamBatcher(self._xbatch,
                                                 self._xb_timeout)
            labels = {"port": str(self.server.port)}
            from ..obs.metrics import Gauge

            self._xb_gauges = [
                REGISTRY.register(Gauge(n, dict(labels), fn=f))
                for n, f in (
                    # fill fraction of the last dispatched bucket and
                    # live bucket occupancy: the "is the device seeing
                    # full tiles" evidence the profiler reads
                    ("nns_xbatch_fill", lambda: self._xb_last_fill),
                    ("nns_xbatch_occupancy",
                     lambda: self._xb_bucket.fill))]
            self._m_xb_batched = REGISTRY.counter(
                "nns_xbatch_batched_total", **labels)
            self._m_xb_solo = REGISTRY.counter(
                "nns_xbatch_solo_total", **labels)
            self._m_xb_frames = REGISTRY.counter(
                "nns_xbatch_frames_total", **labels)
        self._mqtt = None
        if str(self.connect_type).lower() == "hybrid":
            # reference HYBRID (tensor_query_serversrc.c via
            # nnstreamer-edge): dest-host/dest-port address the MQTT
            # broker; the server advertises its own data address as a
            # retained record so clients discover it by topic alone
            from .mqtt import MqttClient

            if self.topic in (None, ""):
                raise ValueError(f"{self.name}: connect-type=HYBRID "
                                 "requires topic")
            self._mqtt = MqttClient(str(self.dest_host),
                                    int(self.dest_port),
                                    f"nns-query-srv-{self.name}")
            adv = str(self.advertise_host or self.host)
            self._mqtt.publish(
                f"nns/query/{self.topic}",
                f"{adv}:{self.server.port}".encode(), retain=True)

    def stop(self):
        if getattr(self, "_xb_gauges", None):
            from ..obs.metrics import REGISTRY

            for g in self._xb_gauges:
                REGISTRY.unregister(g)
            self._xb_gauges = []
        if getattr(self, "_mqtt", None) is not None:
            try:
                # clear the retained record: late clients must see "no
                # record", not a dead address
                self._mqtt.publish(f"nns/query/{self.topic}", b"",
                                   retain=True)
            except OSError:
                pass
            self._mqtt.close()
            self._mqtt = None
        super().stop()

    @property
    def bound_port(self) -> int:
        return self.server.port

    def health_state(self):
        srv = getattr(self, "server", None)
        if srv is not None and srv.draining:
            return "draining"
        return None

    def drain(self, deadline: float = 5.0) -> None:
        """Pipeline.drain hook: stop admitting (new frames shed with a
        retry-after), finish in-flight replies, close the server, and
        drop it from the server table so a later play() gets a fresh
        one."""
        srv = getattr(self, "server", None)
        if srv is not None:
            srv.drain(deadline)
            shutdown_server(int(self.id))

    def negotiate(self) -> Caps:
        if not self.caps:
            raise ValueError(f"{self.name}: caps property required")
        c = self.caps
        return Caps.from_string(c) if isinstance(c, str) else c

    def _note_admission(self, buf: TensorBuffer,
                        deq_ns: Optional[int] = None) -> TensorBuffer:
        """Convert the server's arrival stamp into a deferred
        admission-wait annotation (emitted by Source._loop at the one
        place the frame's seq is assigned — no shadow counter to keep
        in lockstep.  The T_TRACE piggyback then carves it out of the
        client's wire time)."""
        pl = self.pipeline
        if pl is not None and pl.tracer is not None:
            enq = buf.extra.pop("nns_enq_ns", None)
            if enq is not None and pl.tracer.ring is not None:
                from ..obs.clock import mono_ns

                buf.extra["nns_admission_ns"] = (
                    enq, mono_ns() if deq_ns is None else deq_ns)
        return buf

    def create(self) -> Optional[TensorBuffer]:
        if getattr(self, "_xbatch", 1) > 1:
            return self._create_batched()
        while not self._halted.is_set():
            try:
                buf = self.server.incoming.get(timeout=0.1)
            except _queue.Empty:
                continue
            return self._note_admission(buf)
        return None

    @staticmethod
    def _frame_sig(buf: TensorBuffer):
        return tuple((tuple(t.shape), str(getattr(t, "dtype", "")))
                     for t in buf.tensors)

    def _create_batched(self) -> Optional[TensorBuffer]:
        """Cross-stream bucket collect: block for the first admitted
        frame, then coalesce whatever the client population has queued —
        greedily at ``batch-timeout-ms=0`` (the previous bucket's
        service time is the collect window), or waiting up to the
        residents' QoS-scaled budgets to fill the bucket.  The fill
        TARGET is ``min(batch, connected clients)``: synchronous clients
        hold at most one outstanding frame each, so waiting for more
        than the population can deliver is provably pure latency.

        A drain (``QueryServer.drain``) or pipeline halt flushes the
        partial bucket immediately — resident frames are ADMITTED
        (inflight-counted) and must be dispatched, never dropped.
        Frames whose tensor signature differs from the bucket's (flex
        caps) close the bucket and open the next one, preserving
        arrival order."""
        srv = self.server
        bucket = self._xb_bucket
        pl = self.pipeline
        tracer = pl.tracer if pl is not None else None
        rec = tracer is not None and tracer.ring is not None
        mono_ns = None
        if rec:
            from ..obs.clock import mono_ns

        first = self._xb_hold
        self._xb_hold = None
        while first is None:
            if self._halted.is_set():
                return None
            try:
                first = srv.incoming.get(timeout=0.1)
            except _queue.Empty:
                continue
        if rec:
            first.extra["nns_deq_ns"] = mono_ns()
        sig = self._frame_sig(first)
        timeout = self._xb_timeout
        bucket.add(first, bucket_budget(first.extra.get("nns_class"),
                                        timeout))
        while not bucket.full() and not self._halted.is_set() \
                and not srv.draining:
            # fill target: never wait for frames the connected-client
            # population cannot have outstanding
            if bucket.fill >= min(bucket.capacity,
                                  max(1, len(srv._clients))):
                break
            wait = min(bucket.remaining(), 0.05)
            try:
                buf = (srv.incoming.get_nowait() if wait <= 0
                       else srv.incoming.get(timeout=wait))
            except _queue.Empty:
                if wait <= 0 or bucket.expired():
                    break
                continue
            if rec:
                buf.extra["nns_deq_ns"] = mono_ns()
            if self._frame_sig(buf) != sig:
                self._xb_hold = buf    # opener of the NEXT bucket
                break
            bucket.add(buf, bucket_budget(buf.extra.get("nns_class"),
                                          timeout))
        bufs = bucket.take()
        n = len(bufs)
        self._xb_last_fill = n / bucket.capacity
        if n == 1:
            self._m_xb_solo.inc()
            solo = bufs[0]
            solo.extra.pop("nns_deq_ns", None)
            return self._note_admission(solo)
        self._m_xb_batched.inc()
        self._m_xb_frames.inc(n)
        import numpy as np

        tensors = [np.stack([np.asarray(b.tensors[k]) for b in bufs])
                   for k in range(bufs[0].num_tensors)]
        spans = None
        if rec:
            # per-frame residency evidence (obs/attrib.py): arrival →
            # dequeue is admission-wait, dequeue → bucket dispatch is
            # queue-wait.  Deferred to Source._loop (nns_xb_spans) so
            # every span carries the batch buffer's assigned seq; each
            # frame's own trace id routes it to that client's merged
            # timeline via the T_TRACE piggyback.
            disp_ns = mono_ns()
            spans = []
            for b in bufs:
                enq = b.extra.pop("nns_enq_ns", None)
                deq = b.extra.pop("nns_deq_ns", disp_ns)
                ctx = b.extra.get("nns_trace")
                tid = ctx.trace_id if ctx is not None else 0
                if enq is not None:
                    spans.append(("admission-wait", enq, deq, tid))
                spans.append(("queue-wait", deq, disp_ns, tid))
        else:
            for b in bufs:
                b.extra.pop("nns_enq_ns", None)
                b.extra.pop("nns_deq_ns", None)
        out = TensorBuffer(tensors=tensors, pts=bufs[0].pts)
        # the per-frame extras (client id, wire seq, QoS class, trace
        # context) ride the meta to the serversink split; the stacked
        # copy above is the bucket's h2d staging, so the per-frame
        # pooled slabs release right here
        out.extra["nns_xbatch"] = XBatchMeta(
            [b.extra for b in bufs], [b.pts for b in bufs],
            bucket.capacity)
        if spans:
            out.extra["nns_xb_spans"] = spans
        return out


@register_element
class TensorQueryServerSink(Element):
    """Sends pipeline results back to the originating client."""

    FACTORY = "tensor_query_serversink"
    PROPERTIES = {
        "id": (0, "server table id"),
        "async-replies": (False, "cross-stream batching: move the "
                                 "reply split (host materialization + "
                                 "per-row sends) onto ONE ordered "
                                 "pusher thread so the serving thread "
                                 "collects/dispatches the next bucket "
                                 "meanwhile.  Wins when device dispatch "
                                 "is truly asynchronous (accelerators); "
                                 "on small CPU hosts the two threads "
                                 "contend for the same cores and "
                                 "latency suffers — hence opt-in"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def start(self):
        # LAZY lookup: creating the server here would race the paired
        # serversrc's start — if the sink started first, its bare
        # get_server(id) would create the server with DEFAULT overload
        # settings and silently discard the src's queue-depth / shed /
        # capacity-rps / send-timeout properties.  Buffers only reach
        # chain() after the src produced them, so by first use the
        # src-configured server exists.
        self.server = None
        # async reply worker (opt-in via async-replies, spawned at the
        # first cross-stream batch buffer): the reply split — host materialization (the
        # device sync), per-row framing, N socket sends — moves off the
        # serving thread onto ONE ordered pusher (the PR 3 reorder-
        # pusher shape: strict FIFO, so per-client seq order is
        # untouched).  The serving thread is then free to collect and
        # dispatch bucket k+1 while the device computes bucket k and
        # the pusher answers bucket k-1 — the stages overlap instead of
        # serializing into one long cycle.  Depth 1 (double buffering):
        # one bucket being answered while one is collected/dispatched —
        # deeper queues stack concurrent device executions, which
        # oversubscribes the backend's intra-op pool and inflates
        # latency without adding throughput.
        self._rq: Optional[_queue.Queue] = None
        self._rthread: Optional[threading.Thread] = None

    def stop(self):
        self._stop_reply_worker()
        super().stop()

    def _start_reply_worker(self) -> None:
        self._rq = _queue.Queue(maxsize=1)
        self._rthread = threading.Thread(
            target=self._reply_loop, daemon=True,
            name=f"reply-push:{self.name}")
        self._rthread.start()

    def _stop_reply_worker(self) -> None:
        rq, self._rq = self._rq, None
        if rq is not None:
            rq.put(None)
            if self._rthread is not None:
                self._rthread.join(timeout=10)
                self._rthread = None

    def _reply_loop(self) -> None:
        while True:
            buf = self._rq.get()
            try:
                if buf is None:
                    return
                xb = buf.extra.get("nns_xbatch")
                if xb is None:
                    self.server.reply(buf)
                else:
                    self._reply_batch(self.server, buf, xb)
            finally:
                self._rq.task_done()

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        server = self.server
        if server is None:
            server = self.server = get_server(int(self.id))
        # publish the serving pipeline's tracer (one attr store per
        # reply): when it records spans, QueryServer.reply piggybacks
        # them to the requesting client as T_TRACE
        server.obs_tracer = (self.pipeline.tracer
                             if self.pipeline is not None else None)
        xb = buf.extra.get("nns_xbatch")
        if xb is not None and self._rq is None \
                and parse_bool(self.async_replies):
            self._start_reply_worker()
        if self._rq is not None:
            # once the worker exists EVERY buffer rides it (a solo
            # frame jumping the queue would answer ahead of an earlier
            # bucket's rows); chain() was serial before the switch, so
            # order across the transition holds too
            self._rq.put(buf)
            return FlowReturn.OK
        if xb is None:
            server.reply(buf)
            return FlowReturn.OK
        self._reply_batch(server, buf, xb)
        return FlowReturn.OK

    def _reply_batch(self, server, buf: TensorBuffer, xb) -> None:
        """Split a cross-stream batch back into per-client replies, in
        bucket (= per-client arrival) order — exact per-client seq order
        by construction: one serving thread collects, dispatches and
        splits, so client *c*'s row *i* is always answered before its
        row *i+1*.  Padding rows (``>= xb.n``, partial-bucket padded
        invokes) are never replied."""
        tracer = server.obs_tracer
        rec = tracer is not None and getattr(tracer, "ring", None) \
            is not None
        t0 = 0
        if rec:
            import time as _time

            t0 = _time.monotonic_ns()
        # ONE host materialization per output tensor for the whole
        # bucket (TensorBuffer.np is the device sync point — the shared
        # device window every bucket peer overlaps); rows are zero-copy
        # views into it
        mats = [buf.np(k) for k in range(buf.num_tensors)]
        if rec:
            import time as _time

            t1 = _time.monotonic_ns()
            seq = buf.extra.get("nns_seq", -1)
            for extra in xb.extras:
                ctx = extra.get("nns_trace")
                if ctx is not None and ctx.trace_id:
                    tracer.annotate_span("device-invoke", t0, t1,
                                         seq=seq, trace_id=ctx.trace_id)
        for i in range(xb.n):
            frame = TensorBuffer(tensors=[m[i] for m in mats],
                                 pts=xb.pts[i], extra=xb.extras[i])
            server.reply(frame)

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            rq = self._rq
            if rq is not None:
                # every queued reply precedes EOS: admitted frames must
                # be ANSWERED, and drain's inflight accounting only
                # converges once the pusher has sent them
                rq.join()
            self.post_eos_reached()
