"""Tensor query server: serve pipeline inference to remote clients.

Parity with the reference server trio (SURVEY.md §2.7):
- gst/nnstreamer/tensor_query/tensor_query_serversrc.c (receive → queue →
  push into the serving pipeline)
- tensor_query_serversink.c (send answers matched by client id meta)
- tensor_query_server.c (shared server-data table pairing src/sink by id)

The transport thread owns the sockets; client identity rides in
``buf.extra["query_client_id"]`` (the role of GstMeta in
gst/nnstreamer/tensor_meta.c).
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Dict, Optional

from ..analysis.sanitizer import make_condition, make_lock
from ..obs.clock import wall_us
from ..obs.span import TraceContext
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer, default_pool
from ..tensor.caps_util import tensors_template_caps
from ..utils.conf import parse_bool
from .overload import (DEFAULT_QOS, QOS_CLASSES, AdmissionController,
                       TokenBucket, qos_of_class)
from .protocol import (Message, T_BYE, T_DATA, T_HELLO, T_PING, T_PONG,
                       T_REPLY, T_SHED, T_TRACE, decode_tensors, recv_msg,
                       send_msg, send_tensors, shutdown_close)

#: default bound on the server's incoming frame queue (frames, not
#: bytes): deep enough that bursty-but-sustainable traffic never sheds,
#: shallow enough that queued latency stays bounded (256 frames at the
#: measured ~2 ms/query loopback service time is ~0.5 s of backlog)
DEFAULT_QUEUE_DEPTH = 256
#: default per-connection socket send timeout: a client that stops
#: draining replies for this long is a zombie and gets evicted, instead
#: of wedging the serving pipeline thread inside reply()
DEFAULT_SEND_TIMEOUT = 5.0


class QueryServer:
    """Accepts clients, queues incoming frames, routes replies by client id.

    The shared table (reference tensor_query_server.c:76-238) pairs the
    serversrc and serversink elements of one serving pipeline.

    Overload safety (query/overload.py): ``incoming`` is BOUNDED
    (``queue_depth`` frames) and every DATA frame passes admission
    control before its tensors pin a pooled slab — a refused request is
    answered with an explicit ``T_SHED`` carrying a retry-after hint,
    chosen by QoS class (bronze sheds first, gold last; per-connection
    class negotiated in the T_HELLO handshake).  ``drain(deadline)``
    stops admitting, finishes in-flight replies, then closes — the
    server half of the pipeline ``draining`` lifecycle state.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH,
                 admission: Optional[AdmissionController] = None,
                 shed: bool = True,
                 send_timeout: float = DEFAULT_SEND_TIMEOUT):
        self.host = host
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self.queue_depth = max(1, int(queue_depth))
        self.incoming: _queue.Queue = _queue.Queue(maxsize=self.queue_depth)
        #: admit-or-shed decider; ``shed=False`` disables shedding
        #: entirely (overload degrades to per-connection backpressure
        #: on the bounded queue — the pre-overload-layer behavior,
        #: minus the unbounded memory growth)
        self.admission = (admission if admission is not None
                          else AdmissionController()) if shed else None
        self.send_timeout = float(send_timeout)
        self._clients: Dict[int, socket.socket] = {}
        # per-client send locks: the reader thread's handshake/pong
        # replies must not interleave with a partially-written T_REPLY
        # from the pipeline thread (mirror of the client's _send_lock)
        self._send_locks: Dict[int, threading.Lock] = {}
        self._qos: Dict[int, str] = {}   # client id -> negotiated class
        self._caps_str: Optional[str] = None
        self._next_id = 1
        #: serving pipeline's tracer (set by the serversink element);
        #: when it records spans, replies piggyback them as T_TRACE so
        #: the client merges both processes into one timeline
        self.obs_tracer = None
        self._span_cursors: Dict[int, int] = {}   # client id -> ring pos
        self._lock = make_lock("query.registry")
        self._stop = threading.Event()
        self._draining = threading.Event()
        #: admitted-minus-replied frames; drain() waits for zero
        self._inflight = 0
        self._drain_cv = make_condition("query.registry")
        self.peak_depth = 0
        # scrape-time gauges for the soak harness: connected-client
        # count / queue depth / shed rate are lazy callables (zero
        # per-frame cost); admit/shed counters are one inc per decision
        from ..obs.metrics import REGISTRY

        self._m_clients = REGISTRY.gauge(
            "nns_query_server_clients", fn=lambda: len(self._clients),
            port=str(self.port))
        self._m_accepted = REGISTRY.counter(
            "nns_query_server_accepted_total", port=str(self.port))
        self._m_depth = REGISTRY.gauge(
            "nns_query_server_queue_depth",
            fn=self.incoming.qsize, port=str(self.port))
        self._m_peak = REGISTRY.gauge(
            "nns_query_server_queue_peak",
            fn=lambda: self.peak_depth, port=str(self.port))
        self._m_admitted = {
            c: REGISTRY.counter("nns_query_server_admitted_total",
                                port=str(self.port), qos=c)
            for c in QOS_CLASSES}
        self._m_shed = {
            c: REGISTRY.counter("nns_query_server_shed_total",
                                port=str(self.port), qos=c)
            for c in QOS_CLASSES}
        self._m_shed_rate = REGISTRY.gauge(
            "nns_query_server_shed_rate", fn=self._shed_rate,
            port=str(self.port))
        self._m_evicted = REGISTRY.counter(
            "nns_query_server_evicted_total", port=str(self.port))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="query-accept")
        self._accept_thread.start()

    def _shed_rate(self) -> float:
        shed = sum(c.value for c in self._m_shed.values())
        admitted = sum(c.value for c in self._m_admitted.values())
        return shed / max(1, shed + admitted)

    def counters(self) -> Dict[str, Dict[str, int]]:
        """Point-in-time admit/shed counts by QoS class (test/verdict
        surface; the live metrics ride the registry)."""
        return {"admitted": {c: m.value
                             for c, m in self._m_admitted.items()},
                "shed": {c: m.value for c, m in self._m_shed.items()}}

    def set_caps_string(self, caps: str) -> None:
        self._caps_str = caps

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # bound EVERY per-connection send path: a client that stops
            # draining its socket can only stall a send for
            # send_timeout before it is evicted, instead of wedging the
            # pipeline thread inside reply() forever.  The same timeout
            # applies to the reader's recv — protocol.recv_msg treats
            # an idle timeout as retryable, so quiet clients survive.
            if self.send_timeout > 0:
                conn.settimeout(self.send_timeout)
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                self._clients[cid] = conn
                self._send_locks[cid] = make_lock("query.send")
            self._m_accepted.inc()
            threading.Thread(target=self._client_loop, args=(cid, conn),
                             daemon=True, name=f"query-client-{cid}").start()

    def _admit_frame(self, cid: int, msg: Message) -> Optional[float]:
        """Admission decision for one DATA frame: ``None`` admits, a
        float sheds with that retry-after hint (seconds).  Header-only:
        runs BEFORE the payload is decoded into tensors, so a shed
        request's slab goes straight back to the pool."""
        if self.admission is None:
            return None
        qos = self._qos.get(cid, DEFAULT_QOS)
        return self.admission.admit(qos, self.incoming.qsize(),
                                    self.queue_depth)

    def _send_shed(self, conn, slock, cid: int, seq: int,
                   retry_after_s: float) -> None:
        qos = self._qos.get(cid, DEFAULT_QOS)
        self._m_shed[qos].inc()
        with slock:
            send_msg(conn, Message(
                T_SHED, client_id=cid, seq=seq, epoch_us=wall_us(),
                payload=str(int(retry_after_s * 1000)).encode()))

    def _client_loop(self, cid: int, conn: socket.socket) -> None:
        # snapshot: stop() clears the dict concurrently, and a KeyError
        # here would escape the except-OSError below
        slock = self._send_locks.get(cid) or make_lock("query.send")
        pool = default_pool()
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn, pool=pool)
                except TimeoutError:   # idle socket on a bounded-send
                    continue           # connection: keep listening
                except ValueError:   # bad magic / CRC: drop the connection
                    break
                if msg is None or msg.type == T_BYE:
                    break
                if msg.type == T_HELLO:
                    # capability handshake: record the client's QoS
                    # declaration (``qos=<class>`` payload —
                    # query/overload.py), reply with server caps string
                    payload = bytes(msg.payload or b"")
                    if payload.startswith(b"qos="):
                        qos = qos_of_class(payload[4:].decode(
                            "utf-8", "replace"))
                        if qos is not None:
                            with self._lock:
                                self._qos[cid] = qos
                    with slock:
                        send_msg(conn, Message(T_HELLO, client_id=cid,
                                               payload=(self._caps_str
                                                        or "").encode()))
                    continue
                if msg.type == T_PING:
                    # liveness heartbeat: echo seq+payload immediately,
                    # out of band with DATA/REPLY (query/resilience.py).
                    # The pong also stamps this host's wall clock: a
                    # ping round trip has near-zero service time, so it
                    # is the UNBIASED clock-offset sample (obs/clock.py)
                    # — a reply stamp rides on top of model latency.
                    with slock:
                        send_msg(conn, Message(T_PONG, client_id=cid,
                                               seq=msg.seq,
                                               epoch_us=wall_us(),
                                               payload=msg.payload))
                    continue
                if msg.type == T_DATA:
                    # admission BEFORE tensor decode: a shed frame's
                    # pooled payload slab releases immediately instead
                    # of pinning memory through the serving pipeline
                    retry_after = self._admit_frame(cid, msg)
                    if retry_after is not None:
                        if msg.lease is not None:
                            msg.payload = b""
                            msg.lease.release()
                        self._send_shed(conn, slock, cid, msg.seq,
                                        retry_after)
                        continue
                    buf = TensorBuffer(tensors=decode_tensors(msg.payload),
                                       pts=msg.pts, lease=msg.lease)
                    buf.extra["query_client_id"] = cid
                    buf.extra["query_seq"] = msg.seq
                    buf.extra["nns_class"] = qos = self._qos.get(
                        cid, DEFAULT_QOS)
                    if msg.trace_id:
                        # restore the client's trace context: spans this
                        # buffer produces in the serving pipeline record
                        # under the client's trace id (obs/span.py)
                        buf.extra["nns_trace"] = TraceContext(
                            msg.trace_id, msg.span_id, msg.origin_us)
                    self._enqueue(conn, slock, cid, qos, buf)
        except OSError:
            pass   # link reset under us (recv, or a handshake/pong send)
        finally:
            with self._lock:
                self._clients.pop(cid, None)
                self._send_locks.pop(cid, None)
                self._qos.pop(cid, None)
                # client ids are never reused: an unreaped cursor per
                # connection ever made is a slow leak on a long server
                self._span_cursors.pop(cid, None)
            conn.close()

    def _dec_inflight(self) -> None:
        with self._drain_cv:
            if self._inflight > 0:
                self._inflight -= 1
                if self._inflight == 0:
                    self._drain_cv.notify_all()

    def _enqueue(self, conn, slock, cid: int, qos: str,
                 buf: TensorBuffer) -> None:
        """Admit ``buf`` into the bounded queue.  With shedding enabled
        a full queue sheds (the queue bound is the hard watermark the
        policy's soft watermarks sit under); without it, the put blocks
        — per-connection backpressure, woken by stop().

        The in-flight count is raised BEFORE the put: the pipeline
        thread can dequeue and reply the instant the frame lands, and
        a decrement racing ahead of the increment would leave a
        permanent +1 skew that makes drain() time out forever."""
        tracer = self.obs_tracer
        if tracer is not None and tracer.ring is not None:
            # wait-state attribution (obs/attrib.py): arrival stamp so
            # the serversrc can annotate this frame's admission-wait —
            # the time it sat in the bounded queue before the serving
            # pipeline picked it up.  Untraced servers pay one attr
            # read + None test per frame.
            from ..obs.clock import mono_ns

            buf.extra["nns_enq_ns"] = mono_ns()
        with self._drain_cv:
            self._inflight += 1
        while not self._stop.is_set():
            try:
                self.incoming.put(buf, timeout=0.25)
            except _queue.Full:
                if self.admission is not None:
                    self._dec_inflight()   # refused after all
                    buf.lease = None   # buffer dies here: drop its slab
                    self._send_shed(conn, slock, cid,
                                    buf.extra.get("query_seq", 0),
                                    retry_after_s=0.25)
                    return
                continue
            self._m_admitted[qos].inc()
            depth = self.incoming.qsize()
            if depth > self.peak_depth:
                self.peak_depth = depth
            return
        self._dec_inflight()           # server stopped before the put

    def _trace_piggyback(self, cid: int, ctx: TraceContext
                         ) -> Optional[Message]:
        """T_TRACE message carrying this pipeline's new spans for the
        client's trace, or None when there is nothing to send (no
        span-recording tracer attached, or no new spans)."""
        tracer = self.obs_tracer
        if tracer is None or getattr(tracer, "ring", None) is None \
                or not ctx.trace_id:
            return None
        import json as _json

        with self._lock:
            cursor = self._span_cursors.get(cid, 0)
        payload, cursor = tracer.publish_spans(cursor,
                                               trace_id=ctx.trace_id)
        with self._lock:
            self._span_cursors[cid] = cursor
        if not payload["spans"]:
            return None
        return Message(T_TRACE, client_id=cid,
                       trace_id=ctx.trace_id,
                       epoch_us=wall_us(),
                       payload=_json.dumps(payload).encode())

    def reply(self, buf: TensorBuffer) -> bool:
        try:
            return self._reply(buf)
        finally:
            # in-flight accounting runs on EVERY outcome — including a
            # reply for a client that disconnected mid-request — so
            # drain() converges exactly when the last admitted frame
            # has been answered (or become unanswerable)
            self._dec_inflight()

    def _reply(self, buf: TensorBuffer) -> bool:
        cid = buf.extra.get("query_client_id")
        with self._lock:
            conn = self._clients.get(cid)
            slock = self._send_locks.get(cid)
        if conn is None:
            return False
        seq = buf.extra.get("query_seq", 0)
        ctx = buf.extra.get("nns_trace") or TraceContext()
        trace_msg = self._trace_piggyback(cid, ctx)
        try:
            if slock is None:
                slock = make_lock("query.send")   # teardown race: one-shot
            with slock:
                # reply stamps: echo the trace context, carry this
                # host's wall clock so the client estimates the offset
                # (obs/clock.py) from the very frames it already sends
                send_tensors(conn, T_REPLY, buf, client_id=cid,
                             seq=seq, pts=buf.pts or 0,
                             epoch_us=wall_us(),
                             trace_id=ctx.trace_id, span_id=ctx.span_id,
                             origin_us=ctx.origin_us)
                if trace_msg is not None:
                    send_msg(conn, trace_msg)
            return True
        except socket.timeout:
            # the bounded send path fired: this client stopped draining
            # its socket.  Evict it — a zombie peer must cost one send
            # timeout, not one timeout per reply forever.
            self._m_evicted.inc()
            with self._lock:
                self._clients.pop(cid, None)
            shutdown_close(conn)
            return False
        except OSError:
            return False

    def drain(self, deadline: float = 5.0) -> bool:
        """Graceful drain: stop admitting (every new DATA frame sheds
        with a retry-after sized past the drain), let in-flight frames
        finish their replies, then close.  Returns True when the last
        in-flight reply completed within ``deadline`` seconds, False on
        a deadline cut (remaining frames are dropped by close()).

        Wired to the pipeline ``draining`` lifecycle state: the
        /healthz endpoint answers 503 while this runs, so load
        balancers route away while existing requests complete.
        """
        self._draining.set()
        if self.admission is None:
            # drain must stop admitting even on a shed=False server:
            # install a controller whose only act is the drain-mode
            # shed-everything answer
            self.admission = AdmissionController()
        self.admission.start_drain(deadline)
        with self._drain_cv:
            ok = self._drain_cv.wait_for(
                lambda: self._inflight <= 0, timeout=max(0.0, deadline))
        self.close()
        return bool(ok)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def close(self) -> None:
        self._stop.set()
        from ..obs.metrics import REGISTRY

        for g in (self._m_clients, self._m_depth, self._m_peak,
                  self._m_shed_rate):
            REGISTRY.unregister(g)
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._clients.values())
            self._clients.clear()
            self._send_locks.clear()
            self._qos.clear()
        for conn in conns:
            # shutdown-then-close: a plain close of a socket another
            # thread is blocked reading sends no FIN (protocol.py)
            shutdown_close(conn)


#: server table: id → QueryServer (pairs serversrc/serversink)
_SERVERS: Dict[int, QueryServer] = {}
_SERVERS_LOCK = make_lock("leaf")


def get_server(server_id: int, host: str = "127.0.0.1",
               port: int = 0,
               queue_depth: Optional[int] = None,
               shed: Optional[bool] = None,
               capacity_rps: float = 0.0,
               send_timeout: Optional[float] = None) -> QueryServer:
    """Server-table lookup; overload-protection kwargs apply only when
    this call CREATES the server (the serversink's bare lookup must not
    reconfigure the serversrc's server)."""
    with _SERVERS_LOCK:
        if server_id not in _SERVERS:
            admission = None
            if capacity_rps and float(capacity_rps) > 0:
                admission = AdmissionController(
                    bucket=TokenBucket(float(capacity_rps)))
            _SERVERS[server_id] = QueryServer(
                host, port,
                queue_depth=(DEFAULT_QUEUE_DEPTH if queue_depth is None
                             else int(queue_depth)),
                admission=admission,
                shed=(True if shed is None else bool(shed)),
                send_timeout=(DEFAULT_SEND_TIMEOUT if send_timeout is None
                              else float(send_timeout)))
        return _SERVERS[server_id]


def shutdown_server(server_id: int) -> None:
    with _SERVERS_LOCK:
        srv = _SERVERS.pop(server_id, None)
    if srv is not None:
        srv.close()


@register_element
class TensorQueryServerSrc(Source):
    """Receives client frames and pushes them into the serving pipeline."""

    FACTORY = "tensor_query_serversrc"
    PROPERTIES = {
        "host": ("127.0.0.1", ""),
        "port": (0, "0 = ephemeral"),
        "id": (0, "server table id"),
        "caps": (None, "caps announced for received tensors"),
        "connect-type": ("tcp", "TCP | HYBRID (reference nicks; hybrid "
                                "advertises this server's address as a "
                                "retained MQTT record under the topic)"),
        "dest-host": ("127.0.0.1", "hybrid: MQTT broker host"),
        "dest-port": (1883, "hybrid: MQTT broker port"),
        "topic": (None, "hybrid: discovery topic"),
        "advertise-host": (None, "address to advertise in the hybrid "
                                 "record (default: host — set it when "
                                 "bound to 0.0.0.0, which is not a "
                                 "reachable address for remote "
                                 "clients)"),
        "queue-depth": (256, "bound on the incoming frame queue; the "
                             "hard watermark the shed policy's soft "
                             "watermarks sit under"),
        "shed": (True, "admission control: refused frames get explicit "
                       "T_SHED answers with retry-after, QoS-tiered "
                       "(bronze first, gold last — query/overload.py); "
                       "false = pure per-connection backpressure on "
                       "the bounded queue"),
        "capacity-rps": (0.0, "token-bucket admission rate in "
                              "requests/s across all clients "
                              "(0 = depth/latency watermarks only)"),
        "send-timeout": (5.0, "per-connection socket send bound in "
                              "seconds; a client that stops draining "
                              "replies for this long is evicted "
                              "(0 = unbounded sends)"),
    }

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        self.server = get_server(int(self.id), str(self.host),
                                 int(self.port),
                                 queue_depth=int(self.queue_depth),
                                 shed=parse_bool(self.shed),
                                 capacity_rps=float(self.capacity_rps),
                                 send_timeout=float(self.send_timeout))
        if self.caps:
            self.server.set_caps_string(str(self.caps))
        self._mqtt = None
        if str(self.connect_type).lower() == "hybrid":
            # reference HYBRID (tensor_query_serversrc.c via
            # nnstreamer-edge): dest-host/dest-port address the MQTT
            # broker; the server advertises its own data address as a
            # retained record so clients discover it by topic alone
            from .mqtt import MqttClient

            if self.topic in (None, ""):
                raise ValueError(f"{self.name}: connect-type=HYBRID "
                                 "requires topic")
            self._mqtt = MqttClient(str(self.dest_host),
                                    int(self.dest_port),
                                    f"nns-query-srv-{self.name}")
            adv = str(self.advertise_host or self.host)
            self._mqtt.publish(
                f"nns/query/{self.topic}",
                f"{adv}:{self.server.port}".encode(), retain=True)

    def stop(self):
        if getattr(self, "_mqtt", None) is not None:
            try:
                # clear the retained record: late clients must see "no
                # record", not a dead address
                self._mqtt.publish(f"nns/query/{self.topic}", b"",
                                   retain=True)
            except OSError:
                pass
            self._mqtt.close()
            self._mqtt = None
        super().stop()

    @property
    def bound_port(self) -> int:
        return self.server.port

    def health_state(self):
        srv = getattr(self, "server", None)
        if srv is not None and srv.draining:
            return "draining"
        return None

    def drain(self, deadline: float = 5.0) -> None:
        """Pipeline.drain hook: stop admitting (new frames shed with a
        retry-after), finish in-flight replies, close the server, and
        drop it from the server table so a later play() gets a fresh
        one."""
        srv = getattr(self, "server", None)
        if srv is not None:
            srv.drain(deadline)
            shutdown_server(int(self.id))

    def negotiate(self) -> Caps:
        if not self.caps:
            raise ValueError(f"{self.name}: caps property required")
        c = self.caps
        return Caps.from_string(c) if isinstance(c, str) else c

    def create(self) -> Optional[TensorBuffer]:
        while not self._halted.is_set():
            try:
                buf = self.server.incoming.get(timeout=0.1)
            except _queue.Empty:
                continue
            pl = self.pipeline
            if pl is not None and pl.tracer is not None:
                enq = buf.extra.pop("nns_enq_ns", None)
                if enq is not None and pl.tracer.ring is not None:
                    # admission-wait: arrival → dequeue.  The span is
                    # DEFERRED to Source._loop, which emits it at the
                    # one place the frame's seq is assigned — no shadow
                    # counter to keep in lockstep.  The T_TRACE
                    # piggyback then carves it out of the client's
                    # wire time.
                    from ..obs.clock import mono_ns

                    buf.extra["nns_admission_ns"] = (enq, mono_ns())
            return buf
        return None


@register_element
class TensorQueryServerSink(Element):
    """Sends pipeline results back to the originating client."""

    FACTORY = "tensor_query_serversink"
    PROPERTIES = {"id": (0, "server table id")}

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def start(self):
        # LAZY lookup: creating the server here would race the paired
        # serversrc's start — if the sink started first, its bare
        # get_server(id) would create the server with DEFAULT overload
        # settings and silently discard the src's queue-depth / shed /
        # capacity-rps / send-timeout properties.  Buffers only reach
        # chain() after the src produced them, so by first use the
        # src-configured server exists.
        self.server = None

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        server = self.server
        if server is None:
            server = self.server = get_server(int(self.id))
        # publish the serving pipeline's tracer (one attr store per
        # reply): when it records spans, QueryServer.reply piggybacks
        # them to the requesting client as T_TRACE
        server.obs_tracer = (self.pipeline.tracer
                             if self.pipeline is not None else None)
        server.reply(buf)
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self.post_eos_reached()
