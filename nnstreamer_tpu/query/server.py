"""Tensor query server: serve pipeline inference to remote clients.

Parity with the reference server trio (SURVEY.md §2.7):
- gst/nnstreamer/tensor_query/tensor_query_serversrc.c (receive → queue →
  push into the serving pipeline)
- tensor_query_serversink.c (send answers matched by client id meta)
- tensor_query_server.c (shared server-data table pairing src/sink by id)

The transport thread owns the sockets; client identity rides in
``buf.extra["query_client_id"]`` (the role of GstMeta in
gst/nnstreamer/tensor_meta.c).
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
from typing import Dict, Optional

from ..analysis.sanitizer import make_lock
from ..obs.clock import wall_us
from ..obs.span import TraceContext
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer, default_pool
from ..tensor.caps_util import tensors_template_caps
from .protocol import (Message, T_BYE, T_DATA, T_HELLO, T_PING, T_PONG,
                       T_REPLY, T_TRACE, decode_tensors, recv_msg,
                       send_msg, send_tensors, shutdown_close)


class QueryServer:
    """Accepts clients, queues incoming frames, routes replies by client id.

    The shared table (reference tensor_query_server.c:76-238) pairs the
    serversrc and serversink elements of one serving pipeline.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        self.incoming: _queue.Queue = _queue.Queue()
        self._clients: Dict[int, socket.socket] = {}
        # per-client send locks: the reader thread's handshake/pong
        # replies must not interleave with a partially-written T_REPLY
        # from the pipeline thread (mirror of the client's _send_lock)
        self._send_locks: Dict[int, threading.Lock] = {}
        self._caps_str: Optional[str] = None
        self._next_id = 1
        #: serving pipeline's tracer (set by the serversink element);
        #: when it records spans, replies piggyback them as T_TRACE so
        #: the client merges both processes into one timeline
        self.obs_tracer = None
        self._span_cursors: Dict[int, int] = {}   # client id -> ring pos
        self._lock = make_lock("query.registry")
        self._stop = threading.Event()
        # scrape-time gauges for the soak harness: connected-client
        # count is a lazy callable (zero per-frame cost); accepts are a
        # per-connection counter, not per-buffer
        from ..obs.metrics import REGISTRY

        self._m_clients = REGISTRY.gauge(
            "nns_query_server_clients", fn=lambda: len(self._clients),
            port=str(self.port))
        self._m_accepted = REGISTRY.counter(
            "nns_query_server_accepted_total", port=str(self.port))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="query-accept")
        self._accept_thread.start()

    def set_caps_string(self, caps: str) -> None:
        self._caps_str = caps

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                self._clients[cid] = conn
                self._send_locks[cid] = make_lock("query.send")
            self._m_accepted.inc()
            threading.Thread(target=self._client_loop, args=(cid, conn),
                             daemon=True, name=f"query-client-{cid}").start()

    def _client_loop(self, cid: int, conn: socket.socket) -> None:
        # snapshot: stop() clears the dict concurrently, and a KeyError
        # here would escape the except-OSError below
        slock = self._send_locks.get(cid) or make_lock("query.send")
        pool = default_pool()
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn, pool=pool)
                except ValueError:   # bad magic / CRC: drop the connection
                    break
                if msg is None or msg.type == T_BYE:
                    break
                if msg.type == T_HELLO:
                    # capability handshake: reply with server caps string
                    with slock:
                        send_msg(conn, Message(T_HELLO, client_id=cid,
                                               payload=(self._caps_str
                                                        or "").encode()))
                    continue
                if msg.type == T_PING:
                    # liveness heartbeat: echo seq+payload immediately,
                    # out of band with DATA/REPLY (query/resilience.py).
                    # The pong also stamps this host's wall clock: a
                    # ping round trip has near-zero service time, so it
                    # is the UNBIASED clock-offset sample (obs/clock.py)
                    # — a reply stamp rides on top of model latency.
                    with slock:
                        send_msg(conn, Message(T_PONG, client_id=cid,
                                               seq=msg.seq,
                                               epoch_us=wall_us(),
                                               payload=msg.payload))
                    continue
                if msg.type == T_DATA:
                    buf = TensorBuffer(tensors=decode_tensors(msg.payload),
                                       pts=msg.pts, lease=msg.lease)
                    buf.extra["query_client_id"] = cid
                    buf.extra["query_seq"] = msg.seq
                    if msg.trace_id:
                        # restore the client's trace context: spans this
                        # buffer produces in the serving pipeline record
                        # under the client's trace id (obs/span.py)
                        buf.extra["nns_trace"] = TraceContext(
                            msg.trace_id, msg.span_id, msg.origin_us)
                    self.incoming.put(buf)
        except OSError:
            pass   # link reset under us (recv, or a handshake/pong send)
        finally:
            with self._lock:
                self._clients.pop(cid, None)
                self._send_locks.pop(cid, None)
                # client ids are never reused: an unreaped cursor per
                # connection ever made is a slow leak on a long server
                self._span_cursors.pop(cid, None)
            conn.close()

    def _trace_piggyback(self, cid: int, ctx: TraceContext
                         ) -> Optional[Message]:
        """T_TRACE message carrying this pipeline's new spans for the
        client's trace, or None when there is nothing to send (no
        span-recording tracer attached, or no new spans)."""
        tracer = self.obs_tracer
        if tracer is None or getattr(tracer, "ring", None) is None \
                or not ctx.trace_id:
            return None
        import json as _json

        with self._lock:
            cursor = self._span_cursors.get(cid, 0)
        payload, cursor = tracer.publish_spans(cursor,
                                               trace_id=ctx.trace_id)
        with self._lock:
            self._span_cursors[cid] = cursor
        if not payload["spans"]:
            return None
        return Message(T_TRACE, client_id=cid,
                       trace_id=ctx.trace_id,
                       epoch_us=wall_us(),
                       payload=_json.dumps(payload).encode())

    def reply(self, buf: TensorBuffer) -> bool:
        cid = buf.extra.get("query_client_id")
        with self._lock:
            conn = self._clients.get(cid)
            slock = self._send_locks.get(cid)
        if conn is None:
            return False
        seq = buf.extra.get("query_seq", 0)
        ctx = buf.extra.get("nns_trace") or TraceContext()
        trace_msg = self._trace_piggyback(cid, ctx)
        try:
            if slock is None:
                slock = make_lock("query.send")   # teardown race: one-shot
            with slock:
                # reply stamps: echo the trace context, carry this
                # host's wall clock so the client estimates the offset
                # (obs/clock.py) from the very frames it already sends
                send_tensors(conn, T_REPLY, buf, client_id=cid,
                             seq=seq, pts=buf.pts or 0,
                             epoch_us=wall_us(),
                             trace_id=ctx.trace_id, span_id=ctx.span_id,
                             origin_us=ctx.origin_us)
                if trace_msg is not None:
                    send_msg(conn, trace_msg)
            return True
        except OSError:
            return False

    def close(self) -> None:
        self._stop.set()
        from ..obs.metrics import REGISTRY

        REGISTRY.unregister(self._m_clients)
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._clients.values())
            self._clients.clear()
            self._send_locks.clear()
        for conn in conns:
            # shutdown-then-close: a plain close of a socket another
            # thread is blocked reading sends no FIN (protocol.py)
            shutdown_close(conn)


#: server table: id → QueryServer (pairs serversrc/serversink)
_SERVERS: Dict[int, QueryServer] = {}
_SERVERS_LOCK = make_lock("leaf")


def get_server(server_id: int, host: str = "127.0.0.1",
               port: int = 0) -> QueryServer:
    with _SERVERS_LOCK:
        if server_id not in _SERVERS:
            _SERVERS[server_id] = QueryServer(host, port)
        return _SERVERS[server_id]


def shutdown_server(server_id: int) -> None:
    with _SERVERS_LOCK:
        srv = _SERVERS.pop(server_id, None)
    if srv is not None:
        srv.close()


@register_element
class TensorQueryServerSrc(Source):
    """Receives client frames and pushes them into the serving pipeline."""

    FACTORY = "tensor_query_serversrc"
    PROPERTIES = {
        "host": ("127.0.0.1", ""),
        "port": (0, "0 = ephemeral"),
        "id": (0, "server table id"),
        "caps": (None, "caps announced for received tensors"),
        "connect-type": ("tcp", "TCP | HYBRID (reference nicks; hybrid "
                                "advertises this server's address as a "
                                "retained MQTT record under the topic)"),
        "dest-host": ("127.0.0.1", "hybrid: MQTT broker host"),
        "dest-port": (1883, "hybrid: MQTT broker port"),
        "topic": (None, "hybrid: discovery topic"),
        "advertise-host": (None, "address to advertise in the hybrid "
                                 "record (default: host — set it when "
                                 "bound to 0.0.0.0, which is not a "
                                 "reachable address for remote "
                                 "clients)"),
    }

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        self.server = get_server(int(self.id), str(self.host),
                                 int(self.port))
        if self.caps:
            self.server.set_caps_string(str(self.caps))
        self._mqtt = None
        if str(self.connect_type).lower() == "hybrid":
            # reference HYBRID (tensor_query_serversrc.c via
            # nnstreamer-edge): dest-host/dest-port address the MQTT
            # broker; the server advertises its own data address as a
            # retained record so clients discover it by topic alone
            from .mqtt import MqttClient

            if self.topic in (None, ""):
                raise ValueError(f"{self.name}: connect-type=HYBRID "
                                 "requires topic")
            self._mqtt = MqttClient(str(self.dest_host),
                                    int(self.dest_port),
                                    f"nns-query-srv-{self.name}")
            adv = str(self.advertise_host or self.host)
            self._mqtt.publish(
                f"nns/query/{self.topic}",
                f"{adv}:{self.server.port}".encode(), retain=True)

    def stop(self):
        if getattr(self, "_mqtt", None) is not None:
            try:
                # clear the retained record: late clients must see "no
                # record", not a dead address
                self._mqtt.publish(f"nns/query/{self.topic}", b"",
                                   retain=True)
            except OSError:
                pass
            self._mqtt.close()
            self._mqtt = None
        super().stop()

    @property
    def bound_port(self) -> int:
        return self.server.port

    def negotiate(self) -> Caps:
        if not self.caps:
            raise ValueError(f"{self.name}: caps property required")
        c = self.caps
        return Caps.from_string(c) if isinstance(c, str) else c

    def create(self) -> Optional[TensorBuffer]:
        while not self._halted.is_set():
            try:
                return self.server.incoming.get(timeout=0.1)
            except _queue.Empty:
                continue
        return None


@register_element
class TensorQueryServerSink(Element):
    """Sends pipeline results back to the originating client."""

    FACTORY = "tensor_query_serversink"
    PROPERTIES = {"id": (0, "server table id")}

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def start(self):
        self.server = get_server(int(self.id))

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        # publish the serving pipeline's tracer (one attr store per
        # reply): when it records spans, QueryServer.reply piggybacks
        # them to the requesting client as T_TRACE
        self.server.obs_tracer = (self.pipeline.tracer
                                  if self.pipeline is not None else None)
        self.server.reply(buf)
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self.post_eos_reached()
