"""gRPC tensor service: the reference's canonical RPC transport.

Parity with ``ext/nnstreamer/extra/nnstreamer_grpc_common.cc`` (418) /
``…_grpc_protobuf.cc`` (522) / ``…_grpc_flatbuf.cc`` (564) and the
``tensor_src_grpc`` / ``tensor_sink_grpc`` elements
(ext/nnstreamer/tensor_source/tensor_src_grpc.c:71-89,
tensor_sink/tensor_sink_grpc.c): a real HTTP/2 gRPC ``TensorService``
with the reference's two streaming RPCs

    rpc SendTensors (stream Tensors) returns (Empty)   // client → server
    rpc RecvTensors (Empty) returns (stream Tensors)   // server → client

over either IDL (``idl=protobuf`` → ``nnstreamer.proto`` wire messages via
the in-tree protowire codec; ``idl=flatbuf`` → ``nnstreamer.fbs`` wire via
the in-tree flatbuffer runtime).  Messages are (de)serialized by our own
codecs and handed to grpcio as raw bytes, so the frames on the wire are
byte-compatible with the reference service (oracle-tested against
protoc-generated bindings in tests/test_grpc.py).

Like the reference, BOTH elements can run as gRPC server or client
(``server=true/false``): a src in server mode accepts SendTensors pushes;
a src in client mode dials out and pulls RecvTensors; and vice versa for
the sink.  This gives all four pairings of the reference
(src/server, src/client, sink/server, sink/client).
"""

from __future__ import annotations

import queue as _queue
import threading
from fractions import Fraction
from typing import List, Optional

import numpy as np

from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import caps_from_config, tensors_template_caps
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..utils.log import logger
from .resilience import STATS, RetryPolicy

_EOS = object()  # in-queue end-of-stream sentinel


def _redial_client(elem) -> None:
    """Swap ``elem._client`` for a freshly-dialed :class:`GrpcTensorClient`
    (same host/port/IDL) and close the broken one, counting the redial.
    Shared by the src pull loop and the sink send loop."""
    old, elem._client = elem._client, GrpcTensorClient(
        str(elem.host), int(elem.port), elem._codec.idl)
    STATS.incr("grpc.redials")
    try:
        old.close()
    except Exception:  # noqa: BLE001 - channel already broken
        pass


def _method(idl: str, rpc: str) -> str:
    pkg = "nnstreamer.flatbuf" if idl == "flatbuf" else "nnstreamer.protobuf"
    return f"/{pkg}.TensorService/{rpc}"


class _Codec:
    """IDL-selected encode/decode of one stream frame."""

    def __init__(self, idl: str) -> None:
        if idl not in ("protobuf", "flatbuf"):
            raise ValueError(f"grpc: unknown idl {idl!r} "
                             "(protobuf|flatbuf, reference grpc_common.cc)")
        self.idl = idl

    def encode(self, buf: TensorBuffer,
               rate: Optional[Fraction]) -> bytes:
        if self.idl == "flatbuf":
            from ..utils.tensor_flatbuf import encode_tensors

            return encode_tensors([buf.np(i) for i in
                                   range(buf.num_tensors)], rate=rate)
        from ..decoders.serialize import encode_tensors_proto

        return encode_tensors_proto(buf, rate=rate)

    def decode(self, blob: bytes) -> List[np.ndarray]:
        if self.idl == "flatbuf":
            from ..utils.tensor_flatbuf import decode_tensors

            arrays, _rate, _names = decode_tensors(blob)
            return arrays
        from ..decoders.serialize import decode_tensors_proto

        return decode_tensors_proto(blob)


class _BytesService:
    """Generic TensorService endpoint speaking raw bytes (our codecs own
    the message layer).  ``recv_q`` collects frames pushed by remote
    SendTensors callers; RecvTensors streams frames from per-subscriber
    queues fed by :meth:`publish`."""

    def __init__(self, idl: str) -> None:
        self.idl = idl
        # paced by the gRPC stream's flow control; drained every
        # create() on the element streaming thread
        # nnslint: allow(unbounded-queue)
        self.recv_q: _queue.Queue = _queue.Queue()
        self._subs: List[_queue.Queue] = []
        self._lock = threading.Lock()

    # -- rpc implementations -------------------------------------------------
    def _send_tensors(self, request_iterator, context):
        for blob in request_iterator:
            self.recv_q.put(blob)
        return b""  # google.protobuf.Empty

    def _recv_tensors(self, request, context):
        # per-subscriber relay fifo, drained by the subscriber's own
        # RPC response stream (gRPC flow control backpressures it)
        # nnslint: allow(unbounded-queue)
        q: _queue.Queue = _queue.Queue()
        with self._lock:
            self._subs.append(q)
        try:
            while True:
                item = q.get()
                if item is _EOS:
                    return
                yield item
        finally:
            with self._lock:
                if q in self._subs:
                    self._subs.remove(q)

    # -- publisher side ------------------------------------------------------
    def publish(self, blob: bytes) -> None:
        with self._lock:
            subs = list(self._subs)
        for q in subs:
            q.put(blob)

    def finish(self) -> None:
        with self._lock:
            subs = list(self._subs)
        for q in subs:
            q.put(_EOS)

    def handler(self):
        import grpc

        send = grpc.stream_unary_rpc_method_handler(self._send_tensors)
        recv = grpc.unary_stream_rpc_method_handler(self._recv_tensors)
        table = {_method(self.idl, "SendTensors"): send,
                 _method(self.idl, "RecvTensors"): recv}

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                return table.get(details.method)

        return _Handler()


class GrpcTensorServer:
    """Hosts a TensorService on an insecure HTTP/2 port."""

    def __init__(self, host: str, port: int, idl: str) -> None:
        import grpc
        from concurrent import futures

        self.service = _BytesService(idl)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self.service.handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise RuntimeError(f"grpc: cannot bind {host}:{port}")
        self._server.start()

    def close(self) -> None:
        self.service.finish()
        self._server.stop(grace=1.0)


class GrpcTensorClient:
    """Dials a remote TensorService."""

    def __init__(self, host: str, port: int, idl: str) -> None:
        import grpc

        self.idl = idl
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self._send = self._channel.stream_unary(
            _method(idl, "SendTensors"))
        self._recv = self._channel.unary_stream(
            _method(idl, "RecvTensors"))

    def send_stream(self, blob_iterator) -> None:
        """Blocking client-streaming SendTensors call."""
        self._send(blob_iterator)

    def recv_stream(self):
        """Server-streaming RecvTensors call: yields raw frames."""
        return self._recv(b"")

    def close(self) -> None:
        self._channel.close()


def _config_from_arrays(arrays: List[np.ndarray]) -> TensorsConfig:
    return TensorsConfig(
        info=TensorsInfo([TensorInfo.from_np(a) for a in arrays]),
        rate=Fraction(0, 1))


@register_element
class GrpcTensorSrc(Source):
    """``tensor_src_grpc``: receive tensor frames over gRPC.

    server=true (default, reference default too): host the service; remote
    peers push via SendTensors.  server=false: dial ``host:port`` and pull
    the RecvTensors stream.  Output caps come from the ``caps`` property or
    are derived from the first received frame's dims/types.
    """

    FACTORY = "tensor_src_grpc"
    PROPERTIES = {
        "host": ("localhost", "bind/dial host"),
        "port": (55115, "bind/dial port (0 = ephemeral when serving)"),
        "server": (True, "host the service (else dial as client)"),
        "idl": ("protobuf", "message IDL: protobuf|flatbuf"),
        "caps": (None, "override out caps (else derived from first frame)"),
        "num-buffers": (-1, "stop after N buffers, -1 unlimited"),
        "blocking": (True, "reference working-mode flag (accepted for "
                           "launch-line parity; receive here is always "
                           "queue-blocking with a halt check)"),
        "out": (0, "reference READABLE counter: output buffers "
                   "generated so far"),
        "retry": (None, "client mode: redial policy spec 'attempts=4,"
                        "base=0.05,cap=0.5,…' applied when the pulled "
                        "stream breaks mid-run (query/resilience.py); "
                        "unset = a broken stream is end-of-stream (the "
                        "pre-resilience behavior, and the only correct "
                        "one when the server signals EOS by closing)"),
    }

    #: reference G_PARAM_READABLE-only buffer counter — a write is an
    #: error there (critical warning), matching tensor_converter/
    #: decoder/filter; enforced by Element.set_property
    READONLY_PROPERTIES = ("out",)

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        self._codec = _Codec(str(self.idl))
        self._count = 0
        self._first: Optional[List[np.ndarray]] = None
        if self.server:
            self._grpc_server = GrpcTensorServer(str(self.host),
                                                 int(self.port), self._codec.idl)
            self.port = self._grpc_server.port  # readable ephemeral port
            self._fifo = self._grpc_server.service.recv_q
            self._client = None
        else:
            self._grpc_server = None
            self._retry = (RetryPolicy.parse(self.retry)
                           if self.retry not in (None, "") else None)
            self._client = GrpcTensorClient(str(self.host), int(self.port),
                                            self._codec.idl)
            # paced by the gRPC stream; drained every create()
            # nnslint: allow(unbounded-queue)
            self._fifo = _queue.Queue()
            threading.Thread(target=self._pull_loop, daemon=True,
                             name=f"grpc-src:{self.name}").start()

    def _pull_loop(self) -> None:
        import time as _time

        # a clean server-side finish ends the iterator without raising;
        # only the error path is retryable.  Channel creation is lazy
        # (grpcio never fails at dial time), so the backoff loop is
        # driven here: each broken stream costs one delay step, a
        # delivered frame resets the budget.
        attempt = 0
        while True:
            try:
                for blob in self._client.recv_stream():
                    attempt = 0
                    self._fifo.put(blob)
            except Exception as e:  # noqa: BLE001 - stream broke
                if (self._retry is not None and not self._halted.is_set()
                        and attempt + 1 < self._retry.max_attempts):
                    logger.warning("grpc src %s: stream broke (%r), "
                                   "redialing", self.name, e)
                    STATS.incr("grpc.reconnect.retries")
                    _time.sleep(self._retry.delay(attempt))
                    attempt += 1
                    if self._halted.is_set():
                        break   # stop() raced the backoff sleep: a
                                # redial now would leak a live channel
                                # pulling into an unconsumed fifo
                    _redial_client(self)
                    continue
                logger.debug("grpc src %s: recv stream ended: %r",
                             self.name, e)
            break
        self._fifo.put(_EOS)

    def stop(self):
        # halt BEFORE closing the client: closing first makes
        # recv_stream raise while _halted is still clear, and a
        # configured retry policy would redial a live server from a
        # stopped element (leaked channel + unconsumed fifo growth)
        super()._halt()
        if self._grpc_server is not None:
            self._grpc_server.close()
        if self._client is not None:
            self._client.close()

    def _next_blob(self):
        while not self._halted.is_set():
            try:
                return self._fifo.get(timeout=0.1)
            except _queue.Empty:
                continue
        return _EOS

    def negotiate(self) -> Caps:
        if self.caps:
            c = self.caps
            return Caps.from_string(c) if isinstance(c, str) else c
        blob = self._next_blob()
        if blob is _EOS:
            raise ValueError(f"{self.name}: stream closed before first "
                             "frame; cannot derive caps")
        self._first = self._codec.decode(blob)
        return caps_from_config(_config_from_arrays(self._first))

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.num_buffers)
        if n >= 0 and self._count >= n:
            return None
        if self._first is not None:
            arrays, self._first = self._first, None
        else:
            blob = self._next_blob()
            if blob is _EOS:
                return None
            arrays = self._codec.decode(blob)
        self._count += 1
        self.out = self._count    # reference READABLE buffer counter
        return TensorBuffer(tensors=arrays)


@register_element
class GrpcTensorSink(Element):
    """``tensor_sink_grpc``: send the stream over gRPC.

    server=true: host the service; remote peers pull via RecvTensors.
    server=false (reference sink default): dial and push via SendTensors.
    """

    FACTORY = "tensor_sink_grpc"
    PROPERTIES = {
        "host": ("localhost", "bind/dial host"),
        "port": (55115, "bind/dial port (0 = ephemeral when serving)"),
        "server": (False, "host the service (else dial as client)"),
        "idl": ("protobuf", "message IDL: protobuf|flatbuf"),
        "retry": (None, "client mode: redial policy spec 'attempts=4,"
                        "base=0.05,cap=0.5,…' applied when the push "
                        "stream breaks mid-run (frames in flight are "
                        "lost, QoS-0 style); unset = log and stop "
                        "sending (the pre-resilience behavior)"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def start(self):
        self._codec = _Codec(str(self.idl))
        self._rate: Optional[Fraction] = None
        if self.server:
            self._grpc_server = GrpcTensorServer(str(self.host),
                                                 int(self.port), self._codec.idl)
            self.port = self._grpc_server.port
            self._client = None
            self._sendq = None
            self._send_thread = None
        else:
            self._grpc_server = None
            self._retry = (RetryPolicy.parse(self.retry)
                           if self.retry not in (None, "") else None)
            self._client = GrpcTensorClient(str(self.host), int(self.port),
                                            self._codec.idl)
            # fed by chain() on the streaming thread, drained by the
            # send loop: depth is bounded by the pipeline's own
            # upstream queue capacities
            # nnslint: allow(unbounded-queue)
            self._sendq: _queue.Queue = _queue.Queue()
            self._send_thread = threading.Thread(
                target=self._send_loop, daemon=True,
                name=f"grpc-sink:{self.name}")
            self._send_thread.start()

    def _send_loop(self) -> None:
        import time as _time

        attempt = 0
        while True:
            # per-attempt state and queue binding: after a broken RPC,
            # grpcio's consumer thread may still sit in the OLD gen()'s
            # queue.get(); it must not share state (or steal frames /
            # the _EOS sentinel) with the replacement stream
            state = {"eos": False}
            sendq = self._sendq

            def gen(q=sendq, s=state):
                while True:
                    item = q.get()
                    if item is _EOS:
                        s["eos"] = True
                        return
                    yield item

            try:
                self._client.send_stream(gen())
                return
            except Exception as e:  # noqa: BLE001 - stream broke
                # retryable only when a redial policy is set and the
                # stream didn't already consume its EOS sentinel (frames
                # in flight are lost — QoS-0 semantics, like the
                # reference's paho publishes)
                if (self._retry is not None and not state["eos"]
                        and attempt + 1 < self._retry.max_attempts):
                    logger.warning("grpc sink %s: send stream broke "
                                   "(%r), redialing", self.name, e)
                    STATS.incr("grpc.reconnect.retries")
                    # retire the old queue: chain()/stop() move to the
                    # fresh one, and an _EOS posted to the old unblocks
                    # the zombie consumer so it can't swallow new items
                    # fresh queue per redial (same bound as above)
                    # nnslint: allow(unbounded-queue)
                    self._sendq = _queue.Queue()
                    sendq.put(_EOS)
                    _time.sleep(self._retry.delay(attempt))
                    attempt += 1
                    _redial_client(self)
                    continue
                logger.warning("grpc sink %s: send stream failed: %r",
                               self.name, e)
                return

    def stop(self):
        if self._sendq is not None:
            self._sendq.put(_EOS)
        if self._send_thread is not None:
            self._send_thread.join(timeout=10)
        if self._grpc_server is not None:
            self._grpc_server.close()
        if self._client is not None:
            self._client.close()

    def set_caps(self, pad, caps):
        from ..tensor.caps_util import config_from_caps

        self._rate = config_from_caps(caps).rate

    def chain(self, pad, buf):
        blob = self._codec.encode(buf, self._rate)
        if self._grpc_server is not None:
            self._grpc_server.service.publish(blob)
        else:
            self._sendq.put(blob)
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            if self._sendq is not None:
                self._sendq.put(_EOS)
                if self._send_thread is not None:
                    self._send_thread.join(timeout=10)
                    self._send_thread = None
                self._sendq = None
            elif self._grpc_server is not None:
                self._grpc_server.service.finish()
            self.post_eos_reached()
