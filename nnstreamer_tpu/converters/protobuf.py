"""protobuf converter: serialized Tensors messages → tensor frames.

Parity with ext/nnstreamer/tensor_converter/tensor_converter_protobuf.cc
(inverse of the protobuf decoder; schema nnstreamer.proto).
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ..decoders.serialize import decode_tensors_proto
from ..pipeline.caps import Caps, Structure
from ..tensor.buffer import TensorBuffer
from ..tensor.info import TensorsConfig
from . import Converter, register_converter


@register_converter
class ProtobufConverter(Converter):
    NAME = "protobuf"

    def query_caps(self) -> Caps:
        return Caps([Structure("other/protobuf-tensor", {})])

    def get_out_config(self, in_caps: Caps) -> TensorsConfig:
        rate = in_caps.first().get("framerate")
        return TensorsConfig(rate=rate if isinstance(rate, Fraction)
                             else Fraction(0, 1))

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        blob = bytes(np.ascontiguousarray(buf.np(0)).reshape(-1)
                     .view(np.uint8))
        return buf.with_tensors(decode_tensors_proto(blob))
