"""flexbuf converter: serialized flexible-tensor payloads → static tensors.

Role parity with the reference's flexbuf/flatbuf converters
(ext/nnstreamer/tensor_converter/tensor_converter_flexbuf.cc): a byte stream
whose per-buffer payload is our flexible wire format (128-byte meta header +
payload per tensor, nnstreamer_tpu.tensor.meta) converted back to tensors.
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ..pipeline.caps import Caps, Structure
from ..tensor.buffer import TensorBuffer
from ..tensor.info import TensorsConfig
from ..tensor.meta import META_HEADER_SIZE, TensorMetaInfo
from . import Converter, register_converter


@register_converter
class FlexbufConverter(Converter):
    NAME = "flexbuf"

    def query_caps(self) -> Caps:
        return Caps([Structure("other/flexbuf", {})])

    def get_out_config(self, in_caps: Caps) -> TensorsConfig:
        rate = in_caps.first().get("framerate")
        return TensorsConfig(rate=rate if isinstance(rate, Fraction)
                             else Fraction(0, 1))

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        data = np.ascontiguousarray(buf.np(0)).reshape(-1).view(np.uint8)
        raw = data.tobytes()
        tensors = []
        off = 0
        while off + META_HEADER_SIZE <= len(raw):
            meta = TensorMetaInfo.from_bytes(raw[off:off + META_HEADER_SIZE])
            size = meta.data_size
            payload = np.frombuffer(
                raw, np.uint8, count=size, offset=off + META_HEADER_SIZE)
            from ..tensor.types import dim_to_np_shape

            tensors.append(payload.view(meta.dtype.np_dtype)
                           .reshape(dim_to_np_shape(meta.dims)))
            off += META_HEADER_SIZE + size
        return buf.with_tensors(tensors)
