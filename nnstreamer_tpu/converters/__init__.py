"""Converter subplugins: custom media → tensors converters.

Parity with the reference converter subplugin ABI
(gst/nnstreamer/include/nnstreamer_plugin_api_converter.h: name /
convert / get_out_config / query_caps) used by flatbuf/flexbuf/protobuf/
python converters (SURVEY.md §2.6).
"""

from __future__ import annotations

from typing import Dict, Type

from ..pipeline.caps import Caps
from ..tensor.buffer import TensorBuffer
from ..tensor.info import TensorsConfig


class Converter:
    """Converter subplugin ABI."""

    NAME: str = ""

    def query_caps(self) -> Caps:
        """Sink caps this converter accepts."""
        return Caps.any()

    def get_out_config(self, in_caps: Caps) -> TensorsConfig:
        raise NotImplementedError

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        raise NotImplementedError


_CONVERTERS: Dict[str, Type[Converter]] = {}


def register_converter(cls: Type[Converter]) -> Type[Converter]:
    if not cls.NAME:
        raise ValueError(f"{cls.__name__} has no NAME")
    _CONVERTERS[cls.NAME] = cls
    return cls


def find_converter(name: str):
    _ensure_loaded()
    if name not in _CONVERTERS:
        raise KeyError(f"unknown converter {name!r}; known: "
                       f"{sorted(_CONVERTERS)}")
    return _CONVERTERS[name]()


def list_converters():
    _ensure_loaded()
    return sorted(_CONVERTERS)


def _ensure_loaded() -> None:
    from . import flatbuf, flexbuf, protobuf, python  # noqa: F401
