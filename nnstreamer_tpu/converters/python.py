"""Python-script converter: the reference python3 converter contract.

Parity with ext/nnstreamer/tensor_converter/tensor_converter_python3.cc:
``tensor_converter mode=custom-script:<file.py>`` loads a script whose
``class CustomConverter`` implements
``convert(input_array) -> (list[nns.TensorShape], list[np.ndarray(u8)],
rate_n, rate_d)`` — the reference's own fixture
(tests/test_models/models/custom_converter.py) runs unmodified through
the `nnstreamer_python` shim (utils/nns_python_compat.py).
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ..pipeline.caps import Caps
from ..tensor.buffer import TensorBuffer
from ..tensor.info import TensorsConfig
from . import Converter, register_converter


@register_converter
class PythonScriptConverter(Converter):
    NAME = "python3"

    def __init__(self, path: str = "") -> None:
        self._obj = None
        if path:
            self.load(path)

    def load(self, path: str) -> None:
        from ..utils.nns_python_compat import load_user_script

        try:
            got, _ = load_user_script(path, "_nns_pyconv",
                                      "CustomConverter",
                                      "converter_instance")
        except (FileNotFoundError, AttributeError) as exc:
            raise ValueError(f"python3 converter: {exc}") from exc
        self._obj = got() if isinstance(got, type) else got

    def query_caps(self) -> Caps:
        return Caps.any()   # the script decides what bytes it accepts

    def get_out_config(self, in_caps: Caps) -> TensorsConfig:
        rate = in_caps.first().get("framerate")
        return TensorsConfig(rate=rate if isinstance(rate, Fraction)
                             else Fraction(0, 1))

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        if self._obj is None:
            raise ValueError("python3 converter: no script loaded "
                             "(mode=custom-script:<file.py>)")
        arrays = [np.asarray(buf.np(i)) for i in range(buf.num_tensors)]
        shapes, raw, rate_n, rate_d = self._obj.convert(arrays)
        from ..utils.nns_python_compat import to_tensors_info

        info = to_tensors_info(shapes)
        tensors = []
        for ti, blob in zip(info, raw):
            flat = np.asarray(blob).reshape(-1).view(ti.np_dtype)
            tensors.append(flat.reshape(ti.np_shape))
        out = buf.with_tensors(tensors)
        return out
