"""flatbuf converter: serialized ``Tensors`` flatbuffers → tensor frames.

Parity with ext/nnstreamer/tensor_converter/tensor_converter_flatbuf.cc
(inverse of the flatbuf decoder; schema ext/nnstreamer/include/
nnstreamer.fbs), decoded with the in-tree flatbuffer runtime.
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ..pipeline.caps import Caps, Structure
from ..tensor.buffer import TensorBuffer
from ..tensor.info import TensorsConfig
from ..utils.tensor_flatbuf import decode_tensors
from . import Converter, register_converter


@register_converter
class FlatbufConverter(Converter):
    NAME = "flatbuf"

    def query_caps(self) -> Caps:
        return Caps([Structure("other/flatbuf-tensor", {})])

    def get_out_config(self, in_caps: Caps) -> TensorsConfig:
        rate = in_caps.first().get("framerate")
        return TensorsConfig(rate=rate if isinstance(rate, Fraction)
                             else Fraction(0, 1))

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        blob = bytes(np.ascontiguousarray(buf.np(0)).reshape(-1)
                     .view(np.uint8))
        arrays, _rate, _names = decode_tensors(blob)
        return buf.with_tensors(arrays)
