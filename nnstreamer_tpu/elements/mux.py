"""tensor_mux / tensor_demux: frame composition and decomposition.

Parity with gst/nnstreamer/elements/gsttensor_mux.c (N streams → one
multi-tensor frame, PTS-synced via the policies of
:mod:`nnstreamer_tpu.pipeline.clock`) and gsttensor_demux.c (one frame →
N streams, with ``tensorpick`` selection).
"""

from __future__ import annotations

from fractions import Fraction
from typing import Dict, List, Optional

from ..pipeline.clock import CollectPads, SyncMode, parse_sync_option
from ..pipeline.element import CapsEvent, Element, EOSEvent, FlowReturn, Pad
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                static_tensors_caps, tensors_template_caps)
from ..tensor.info import TensorsConfig, TensorsInfo


@register_element
class TensorMux(Element):
    FACTORY = "tensor_mux"
    PROPERTIES = {
        "sync-mode": ("slowest", "nosync|slowest|basepad|refresh"),
        "sync-option": (None, "basepad: '<pad>:<duration_ns>'"),
    }

    def _make_pads(self):
        self.add_src_pad(static_tensors_caps(), "src")

    def request_sink_pad(self) -> Pad:
        return self.add_sink_pad(static_tensors_caps())

    def start(self):
        import threading

        dur, base_pad = parse_sync_option(self.sync_option)
        self._collect = CollectPads(len(self.sink_pads),
                                    SyncMode.from_string(self.sync_mode), dur,
                                    base_pad=base_pad)
        self._pad_index = {p.name: i for i, p in enumerate(self.sink_pads)}
        self._pad_configs: Dict[int, TensorsConfig] = {}
        self._announced = False
        self._sent_eos = False
        self._eos_lock = threading.Lock()

    # -- negotiation: src caps = concatenation of all sink infos -------------
    def set_caps(self, pad, caps):
        idx = self._pad_index[pad.name]
        self._pad_configs[idx] = config_from_caps(caps)
        if len(self._pad_configs) == len(self.sink_pads) and not self._announced:
            infos: List = []
            for i in range(len(self.sink_pads)):
                infos.extend(self._pad_configs[i].info)
            rate = self._pad_configs[0].rate or Fraction(0, 1)
            cfg = TensorsConfig(info=TensorsInfo(list(infos)), rate=rate)
            self._announced = True
            self.announce_src_caps(caps_from_config(cfg))

    def chain(self, pad, buf):
        idx = self._pad_index[pad.name]
        if self._sent_eos:
            return FlowReturn.EOS
        frame_set = self._collect.push(idx, buf)
        if frame_set is None:
            return FlowReturn.OK
        ret = self.push(self._combine(frame_set))
        # an EOS'd pad may just have drained: the stream ends now
        # (reference is_eos re-check per collect, gsttensor_mux.c:505-513)
        if self._collect.exhausted():
            self._send_eos_once()
            return FlowReturn.EOS
        return ret

    def _send_eos_once(self) -> None:
        with self._eos_lock:
            if self._sent_eos:
                return
            self._sent_eos = True
        self.src_pad.push_event(EOSEvent())

    def _combine(self, frame_set: List[TensorBuffer]) -> TensorBuffer:
        tensors = []
        for b in frame_set:
            tensors.extend(b.tensors)
        pts = max((b.pts or 0) for b in frame_set)
        return TensorBuffer(tensors=tensors, pts=pts,
                            duration=frame_set[0].duration)

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            idx = self._pad_index[pad.name]
            if self._collect.set_eos(idx):
                self._send_eos_once()
            else:
                # all pads EOS but not exhausted (basepad/refresh base
                # backlog): drain what the policy can still form, then end
                leftover = self._collect.finalize()
                if leftover is not None:
                    for fs in leftover:
                        self.push(self._combine(fs))
                    self._send_eos_once()
            return
        # forward non-EOS events once (from pad 0 only, to avoid duplicates)
        if self._pad_index[pad.name] == 0:
            super().on_event(pad, event)


@register_element
class TensorDemux(Element):
    FACTORY = "tensor_demux"
    PROPERTIES = {
        "tensorpick": (None, "comma list: which tensors to expose, in order; "
                             "supports 'i' or 'i:j:k' groups per src pad"),
    }

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")

    def request_src_pad(self) -> Pad:
        return self.add_src_pad(static_tensors_caps())

    def _parse_picks(self) -> Optional[List[List[int]]]:
        """One parser for start() AND static_check(): the verifier must
        judge exactly the syntax the runtime accepts."""
        if self.tensorpick in (None, ""):
            return None
        return [[int(x) for x in grp.split(":")]
                for grp in str(self.tensorpick).split(",")]

    def start(self):
        self._picks = self._parse_picks()

    def static_check(self):
        """Verifier hook: a tensorpick that declares fewer groups than
        this demux has linked src pads is the exact mismatch set_caps
        rejects at negotiation — catch it pre-play."""
        try:
            picks = self._parse_picks()
        except ValueError:
            return [("error", f"{self.name}: unparsable tensorpick "
                              f"{self.tensorpick!r}")]
        if picks is not None and len(picks) < len(self.src_pads):
            return [("error",
                     f"{self.name}: {len(self.src_pads)} src pads but "
                     f"tensorpick declares only {len(picks)} tensor "
                     "groups — negotiation would fail")]
        return []

    def _groups(self, num_tensors: int) -> List[List[int]]:
        if self._picks is not None:
            return self._picks
        return [[i] for i in range(num_tensors)]

    def set_caps(self, pad, caps):
        cfg = config_from_caps(caps)
        groups = self._groups(cfg.info.num_tensors)
        if len(groups) < len(self.src_pads):
            raise ValueError(
                f"{self.name}: {len(self.src_pads)} src pads but only "
                f"{len(groups)} tensor groups")
        for sp, grp in zip(self.src_pads, groups):
            infos = TensorsInfo([cfg.info[i].copy() for i in grp])
            out = TensorsConfig(info=infos, rate=cfg.rate)
            sp.push_event(CapsEvent(caps_from_config(out)))

    def chain(self, pad, buf):
        groups = self._groups(buf.num_tensors)
        for sp, grp in zip(self.src_pads, groups):
            out = buf.with_tensors([buf.tensors[i] for i in grp])
            ret = sp.push(out)
            if ret is FlowReturn.ERROR:
                return ret
        return FlowReturn.OK
