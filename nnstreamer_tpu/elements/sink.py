"""Sink elements: tensor_sink (signal emitter), fakesink, filesink.

Parity with gst/nnstreamer/elements/gsttensor_sink.c: an appsink-like
element emitting a ``new-data`` callback per buffer, which is how
applications and all the reference's sink unit tests consume pipeline
output (tests/nnstreamer_sink/unittest_sink.cc).
"""

from __future__ import annotations

import threading
import time
from fractions import Fraction
from typing import Callable, List, Optional

import numpy as np

from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn, QoSEvent
from ..pipeline.registry import register_element
from ..tensor.buffer import SECOND, TensorBuffer


@register_element
class TensorSink(Element):
    FACTORY = "tensor_sink"
    PROPERTIES = {
        "emit-signal": (True, "invoke new-data callbacks"),
        "sync": (False, "render buffers at their PTS against the "
                        "pipeline clock (real-time playback pacing)"),
        "collect": (True, "keep buffers in .results"),
        "max-results": (0, "cap on retained buffers, 0 = unlimited"),
        "qos": (False, "emit upstream QoS events when consuming slower "
                       "than the stream's frame duration"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._callbacks: List[Callable[[TensorBuffer], None]] = []
        self.results: List[TensorBuffer] = []
        self._caps: Optional[Caps] = None
        self._eos = threading.Event()
        self._qos_late = False
        self._unblock = threading.Event()   # stop() aborts a sync wait

    def start(self):
        self._unblock.clear()

    def unblock(self):
        self._unblock.set()

    def stop(self):
        self._unblock.set()

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def connect(self, signal: str, cb: Callable[[TensorBuffer], None]) -> None:
        """GObject-signal-style registration: connect("new-data", fn)."""
        if signal != "new-data":
            raise ValueError(f"unknown signal {signal!r}")
        self._callbacks.append(cb)

    def set_caps(self, pad, caps):
        self._caps = caps

    @property
    def caps(self) -> Optional[Caps]:
        return self._caps

    def _frame_duration_ns(self, buf) -> int:
        if buf.duration:
            return int(buf.duration)
        if self._caps is not None:
            rate = self._caps.first().get("framerate")
            if isinstance(rate, Fraction) and rate > 0:
                return SECOND * rate.denominator // rate.numerator
        return 0

    def chain(self, pad, buf):
        if self.sync and buf.pts is not None and self.pipeline is not None:
            # render at PTS: wait until base_time + pts on the pipeline
            # clock (GStreamer sink sync semantics); stop() unblocks
            base = getattr(self.pipeline, "base_time_ns", None)
            if base is not None:
                target = base + int(buf.pts)
                while not self._unblock.is_set():
                    delta = (target - time.monotonic_ns()) / 1e9
                    if delta <= 0:
                        break
                    self._unblock.wait(delta)   # set() wakes immediately
        t0 = time.monotonic_ns() if self.qos else 0
        if self.collect:
            self.results.append(buf)
            cap = int(self.max_results)
            if cap > 0 and len(self.results) > cap:
                self.results.pop(0)
        if self.emit_signal:
            for cb in self._callbacks:
                cb(buf)
        if self.qos:
            # QoS feedback loop (reference wires real-time sinks' QoS events
            # to tensor_filter throttling, tensor_filter.c:1454-1485): when
            # consuming this buffer took longer than one frame duration,
            # tell upstream how far behind we are.  When a previously-slow
            # consumer catches up, send ONE catch-up event (jitter <= 0) so
            # upstream throttles can clear — without it a single transient
            # stall would throttle the stream forever.
            proc = time.monotonic_ns() - t0
            dur = self._frame_duration_ns(buf)
            if dur and proc > dur:
                self._qos_late = True
                pad.push_upstream_event(QoSEvent(
                    timestamp=buf.pts, jitter_ns=proc - dur,
                    proportion=proc / dur))
            elif dur and self._qos_late:
                self._qos_late = False
                pad.push_upstream_event(QoSEvent(
                    timestamp=buf.pts, jitter_ns=proc - dur,
                    proportion=max(proc / dur, 1e-3)))
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self._eos.set()
            self.post_eos_reached()

    def wait_eos(self, timeout: Optional[float] = None) -> bool:
        return self._eos.wait(timeout)


@register_element
class FakeSink(Element):
    """Discards buffers (GStreamer fakesink role)."""

    FACTORY = "fakesink"

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self.post_eos_reached()


@register_element
class FileSink(Element):
    """Appends raw tensor bytes to a file (multifilesink/filesink role used
    by the reference golden tests to byte-compare outputs)."""

    FACTORY = "filesink"
    PROPERTIES = {"location": (None, "output path")}

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def start(self):
        if not self.location:
            raise ValueError(f"{self.name}: location required")
        self._f = open(str(self.location), "wb")

    def stop(self):
        f = getattr(self, "_f", None)
        if f is not None and not f.closed:
            f.close()

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        for i in range(buf.num_tensors):
            self._f.write(np.ascontiguousarray(buf.np(i)).tobytes())
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self._f.flush()
            self.post_eos_reached()


@register_element
class MultiFileSink(Element):
    """One file PER BUFFER at ``location % index`` (GStreamer
    multifilesink role — the ssat harness tees processed streams into
    indexed files and byte-compares them against goldens, e.g.
    ``multifilesink location=result_%1d.log``)."""

    FACTORY = "multifilesink"
    PROPERTIES = {
        "location": (None, "printf pattern, e.g. result_%1d.log"),
        "index": (0, "first file index"),
    }

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def start(self):
        from .src import _indexed_path

        if not self.location:
            raise ValueError(f"{self.name}: location required")
        self._idx = int(self.index)
        self._indexed_path = _indexed_path
        self._indexed_path(self.location, self._idx, self.name)

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        path = self._indexed_path(self.location, self._idx, self.name)
        with open(path, "wb") as fh:
            for i in range(buf.num_tensors):
                fh.write(np.ascontiguousarray(buf.np(i)).tobytes())
        self._idx += 1
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self.post_eos_reached()
