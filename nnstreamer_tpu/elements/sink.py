"""Sink elements: tensor_sink (signal emitter), fakesink, filesink.

Parity with gst/nnstreamer/elements/gsttensor_sink.c: an appsink-like
element emitting a ``new-data`` callback per buffer, which is how
applications and all the reference's sink unit tests consume pipeline
output (tests/nnstreamer_sink/unittest_sink.cc).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

import numpy as np

from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer


@register_element
class TensorSink(Element):
    FACTORY = "tensor_sink"
    PROPERTIES = {
        "emit-signal": (True, "invoke new-data callbacks"),
        "sync": (False, "no-op (no wall-clock sync yet)"),
        "collect": (True, "keep buffers in .results"),
        "max-results": (0, "cap on retained buffers, 0 = unlimited"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._callbacks: List[Callable[[TensorBuffer], None]] = []
        self.results: List[TensorBuffer] = []
        self._caps: Optional[Caps] = None
        self._eos = threading.Event()

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def connect(self, signal: str, cb: Callable[[TensorBuffer], None]) -> None:
        """GObject-signal-style registration: connect("new-data", fn)."""
        if signal != "new-data":
            raise ValueError(f"unknown signal {signal!r}")
        self._callbacks.append(cb)

    def set_caps(self, pad, caps):
        self._caps = caps

    @property
    def caps(self) -> Optional[Caps]:
        return self._caps

    def chain(self, pad, buf):
        if self.collect:
            self.results.append(buf)
            cap = int(self.max_results)
            if cap > 0 and len(self.results) > cap:
                self.results.pop(0)
        if self.emit_signal:
            for cb in self._callbacks:
                cb(buf)
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self._eos.set()
            self.post_eos_reached()

    def wait_eos(self, timeout: Optional[float] = None) -> bool:
        return self._eos.wait(timeout)


@register_element
class FakeSink(Element):
    """Discards buffers (GStreamer fakesink role)."""

    FACTORY = "fakesink"

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self.post_eos_reached()


@register_element
class FileSink(Element):
    """Appends raw tensor bytes to a file (multifilesink/filesink role used
    by the reference golden tests to byte-compare outputs)."""

    FACTORY = "filesink"
    PROPERTIES = {"location": (None, "output path")}

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")

    def start(self):
        if not self.location:
            raise ValueError(f"{self.name}: location required")
        self._f = open(str(self.location), "wb")

    def stop(self):
        f = getattr(self, "_f", None)
        if f is not None and not f.closed:
            f.close()

    def set_caps(self, pad, caps):
        pass

    def chain(self, pad, buf):
        for i in range(buf.num_tensors):
            self._f.write(np.ascontiguousarray(buf.np(i)).tobytes())
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self._f.flush()
            self.post_eos_reached()
