"""tensor_aggregator: temporal batching/windowing of tensor streams.

Parity with gst/nnstreamer/elements/gsttensor_aggregator.c (fields at
gsttensor_aggregator.h:60-63): collect ``frames-in`` incoming frames,
emit windows of ``frames-out`` with hop ``frames-flush`` (0 = tumbling),
concatenated along ``frames-dim`` — e.g. 300:300 @30fps with frames-out=2
→ 300:300:2 @15fps.

This is also the framework's long-context streaming primitive: windows feed
sequence models, and with large ``frames-out`` the window lands on device as
one batched MXU-friendly tensor.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..pipeline.element import Element, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                static_tensors_caps)
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo


@register_element
class TensorAggregator(Element):
    FACTORY = "tensor_aggregator"
    PROPERTIES = {
        "frames-in": (1, "frames per incoming buffer along frames-dim"),
        "frames-out": (1, "frames per outgoing window"),
        "frames-flush": (0, "hop size in frames; 0 = frames-out (tumbling)"),
        "frames-dim": (None, "reference dim index to stack along; default "
                             "appends a new outermost dim"),
        "concat": (True, "concatenate (True) vs emit list of frames"),
    }

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")
        self.add_src_pad(static_tensors_caps(), "src")

    def start(self):
        self._window: List[np.ndarray] = []
        self._pts: List[int] = []
        fin, fout = int(self.frames_in), int(self.frames_out)
        if fin > 1 and self.frames_dim is None:
            raise ValueError(
                f"{self.name}: frames-in > 1 requires frames-dim")
        if fout % fin:
            raise ValueError(
                f"{self.name}: frames-out={fout} not a multiple of "
                f"frames-in={fin}")
        hop_frames = int(self.frames_flush) or fout
        if hop_frames % fin:
            raise ValueError(
                f"{self.name}: frames-flush={hop_frames} not a multiple of "
                f"frames-in={fin}")
        # buffer counts: each incoming buffer carries frames-in frames
        self._need_bufs = fout // fin
        self._hop_bufs = hop_frames // fin

    def set_caps(self, pad, caps):
        cfg = config_from_caps(caps)
        info = cfg.info[0]
        fin, fout = int(self.frames_in), int(self.frames_out)
        dims = list(info.dims)
        if self.frames_dim is None:
            dims = dims + [fout]
            self._axis_new = True
            self._dim = len(dims) - 1
        else:
            self._dim = int(self.frames_dim)
            self._axis_new = False
            if self._dim >= len(dims):
                # reference dims are 1-padded to the rank limit, so any
                # frames-dim up to rank 8 is addressable
                dims = dims + [1] * (self._dim + 1 - len(dims))
            per_buf = dims[self._dim]
            dims[self._dim] = per_buf * fout // max(fin, 1)
        rate = cfg.rate
        if rate and fout:
            hop = int(self.frames_flush) or fout
            rate = rate / hop
        out = TensorsConfig(
            info=TensorsInfo([TensorInfo(info.dtype, tuple(dims))]),
            rate=rate)
        self.announce_src_caps(caps_from_config(out))

    def chain(self, pad, buf):
        self._window.append(buf.np(0))
        self._pts.append(buf.pts or 0)
        need = self._need_bufs
        if len(self._window) < need:
            return FlowReturn.OK
        if self._axis_new:
            merged = np.stack(self._window[:need], axis=0)
        else:
            frames = [f.reshape((1,) * (self._dim + 1 - f.ndim) + f.shape)
                      if f.ndim <= self._dim else f
                      for f in self._window[:need]]
            axis = frames[0].ndim - 1 - self._dim
            merged = np.concatenate(frames, axis=axis)
        out = TensorBuffer(tensors=[merged], pts=self._pts[0],
                           duration=buf.duration)
        self._window = self._window[self._hop_bufs:]
        self._pts = self._pts[self._hop_bufs:]
        return self.push(out)
