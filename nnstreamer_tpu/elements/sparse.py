"""tensor_sparse_enc / tensor_sparse_dec: static ↔ sparse tensor format.

Parity with gst/nnstreamer/elements/gsttensor_sparseenc.c / sparsedec.c /
sparseutil.c: COO encoding — nonzero values + flat indices — carried behind
the per-buffer meta header (sparse_info.nnz, tensor_typedef.h:263-296).
Wire layout per tensor: 128-byte meta ++ values[nnz] ++ uint32 indices[nnz].
(The reference stores per-rank uint32 index tuples; we store flat uint32
indices — same information, one word per element.)
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ..pipeline.element import Element
from ..pipeline.registry import register_element
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                static_tensors_caps)
from ..tensor.info import TensorInfo, TensorsConfig
from ..tensor.meta import META_HEADER_SIZE, TensorMetaInfo
from ..tensor.types import TensorFormat, dim_to_np_shape


def sparse_encode(arr: np.ndarray) -> bytes:
    """Dense → meta+values+indices blob (reference sparseutil encode loop,
    gsttensor_sparseutil.c:120-180).  Uses the native tensorwire codec when
    libnnstw.so is available."""
    from .. import native
    from ..tensor.info import TensorInfo as _TI

    vals, idx = native.sparse_gather(arr)
    meta = TensorMetaInfo.from_info(_TI.from_np(arr),
                                    format=TensorFormat.SPARSE)
    meta.sparse_nnz = int(idx.size)
    return meta.to_bytes() + vals.tobytes() + idx.tobytes()


def sparse_decode(blob: bytes) -> np.ndarray:
    """meta+values+indices blob → dense (reference sparseutil decode,
    gsttensor_sparseutil.c:31-62)."""
    meta = TensorMetaInfo.from_bytes(blob)
    nnz = meta.sparse_nnz
    esz = meta.dtype.element_size
    vals = np.frombuffer(blob, meta.dtype.np_dtype, count=nnz,
                         offset=META_HEADER_SIZE)
    idx = np.frombuffer(blob, np.uint32, count=nnz,
                        offset=META_HEADER_SIZE + nnz * esz)
    shape = dim_to_np_shape(meta.dims)
    from .. import native

    dense = native.sparse_scatter(vals, idx, int(np.prod(shape)))
    return dense.reshape(shape)


@register_element
class TensorSparseEnc(Element):
    FACTORY = "tensor_sparse_enc"

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")
        from ..tensor.caps_util import tensors_template_caps

        self.add_src_pad(tensors_template_caps(), "src")

    def set_caps(self, pad, caps):
        cfg = config_from_caps(caps)
        out = TensorsConfig(format=TensorFormat.SPARSE,
                            rate=cfg.rate or Fraction(0, 1))
        self.announce_src_caps(caps_from_config(out))

    def chain(self, pad, buf):
        blobs = [np.frombuffer(sparse_encode(buf.np(i)), np.uint8)
                 for i in range(buf.num_tensors)]
        return self.push(buf.with_tensors(blobs))


@register_element
class TensorSparseDec(Element):
    FACTORY = "tensor_sparse_dec"

    def _make_pads(self):
        from ..tensor.caps_util import tensors_template_caps

        self.add_sink_pad(tensors_template_caps(), "sink")
        self.add_src_pad(static_tensors_caps(), "src")

    def start(self):
        self._announced = False

    def set_caps(self, pad, caps):
        self._rate = config_from_caps(caps).rate

    def chain(self, pad, buf):
        dense = [sparse_decode(buf.np(i).tobytes())
                 for i in range(buf.num_tensors)]
        if not self._announced:
            from ..tensor.info import TensorsInfo

            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo.from_np(d) for d in dense]),
                rate=self._rate or Fraction(0, 1))
            self.announce_src_caps(caps_from_config(cfg))
            self._announced = True
        return self.push(buf.with_tensors(dense))
