"""tensor_decoder element: other/tensors → media via a decoder subplugin.

Parity with gst/nnstreamer/elements/gsttensor_decoder.c (mode + option1..9
properties select and configure the subplugin; custom callback mode via
``mode=custom-code`` like the reference tensor_decoder_custom.h).
"""

from __future__ import annotations

from ..decoders import find_decoder
from ..pipeline.caps import Caps
from ..pipeline.element import CustomEvent, Element
from ..pipeline.registry import register_element
from ..tensor.caps_util import config_from_caps, tensors_template_caps


@register_element
class TensorDecoder(Element):
    FACTORY = "tensor_decoder"
    PROPERTIES = dict(
        {"mode": (None, "decoder mode name"),
         # net-new: the device-reduction pushdown (fusing the pure part
         # of decode into the upstream executable) can be disabled to
         # measure its delta or to force the host decode path
         "pushdown": (True, "fuse pure decode reductions into the "
                            "upstream filter executable"),
         "sub-plugins": (None, "reference READABLE property: registered "
                               "decoder modes")},
        **{f"option{i}": (None, f"decoder option {i}") for i in range(1, 10)})

    #: reference G_PARAM_READABLE-only (enforced by Element.set_property)
    READONLY_PROPERTIES = ("sub-plugins",)

    def get_property(self, key):
        if key in ("sub-plugins", "sub_plugins"):
            from ..decoders import list_decoders

            return ",".join(list_decoders())
        return super().get_property(key)

    #: custom callbacks registered via register_decoder_custom (reference
    #: tensor_decoder_custom.h)
    _CUSTOM = {}

    @classmethod
    def register_custom(cls, name, fn):
        cls._CUSTOM[name] = fn

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")
        self.add_src_pad(Caps.any(), "src")

    def start(self):
        mode = str(self.mode or "")
        if not mode:
            raise ValueError(f"{self.name}: mode property required")
        if mode == "custom-code":
            fn = self._CUSTOM.get(str(self.option1))
            if fn is None:
                raise ValueError(
                    f"{self.name}: custom decoder {self.option1!r} "
                    "not registered")
            self._decoder = None
            self._custom_fn = fn
            return
        self._custom_fn = None
        self._decoder = find_decoder(mode)()
        for i in range(1, 10):
            val = getattr(self, f"option{i}")
            if val is not None:
                self._decoder.set_option(i, str(val))

    def set_caps(self, pad, caps):
        self._config = config_from_caps(caps)
        if self._decoder is not None:
            from ..utils.conf import parse_bool

            spec = (self._decoder.device_reduce_spec(self._config)
                    if parse_bool(self.pushdown) else None)
            if spec is not None:
                fn, reduced = spec
                ev = CustomEvent("nns/device-reduce",
                                 {"fn": fn, "out_info": reduced})
                if pad.push_upstream_event(ev):
                    # the filter re-announced reduced caps; that nested
                    # set_caps cascade (where device_reduce_spec returns
                    # None on the already-reduced config) completed the
                    # negotiation — nothing more to announce here
                    return
            self.announce_src_caps(self._decoder.get_out_caps(self._config))
        else:
            from ..pipeline.caps import Structure
            from fractions import Fraction

            self.announce_src_caps(Caps([Structure(
                "application/octet-stream",
                {"framerate": self._config.rate or Fraction(0, 1)})]))

    def _decode_one(self, buf):
        if self._custom_fn is not None:
            return self._custom_fn(buf, self._config)
        return self._decoder.decode(buf, self._config)

    def chain(self, pad, buf):
        return self.push(self._decode_one(buf))

    def plan_step(self):
        return self._decode_one

    def lower_reason(self):
        mode = str(self.mode or "")
        if mode == "custom-code":
            return "custom-code decoders run arbitrary host callbacks"
        try:
            dec = find_decoder(mode) if mode else None
        except KeyError:
            dec = None
        if dec is None or "lower_decode" not in vars(dec):
            return (f"decoder mode {mode!r} has no lower_decode "
                    "(pure-tensor lowering hook)")
        return None

    def lower_step(self):
        if getattr(self, "_custom_fn", None) is not None \
                or getattr(self, "_decoder", None) is None \
                or getattr(self, "_config", None) is None:
            return None
        spec = self._decoder.lower_decode(self._config)
        if spec is None:
            return None
        fn, needs_post = spec
        from ..pipeline.element import LoweredStep

        post = self._decode_one if needs_post else None
        return LoweredStep(lambda params, ts: fn(ts), post=post)
