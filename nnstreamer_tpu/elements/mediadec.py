"""Media decode elements: pngdec, pnmdec, wavparse.

The reference's golden pipelines put GStreamer media plugins in front of
``tensor_converter`` (``filesrc ! pngdec ! videoconvert …``,
``filesrc ! wavparse …`` — e.g. tests/nnstreamer_filter_tensorflow2_lite/
runTest.sh, tests/nnstreamer_converter/).  These elements fill the same
slots with the in-tree decoders (utils/mediadec.py — stdlib zlib, no
PIL/libpng/libsndfile):

- ``pngdec`` / ``pnmdec``: accumulate the upstream byte stream until EOS
  (images arrive as one or more filesrc chunks), decode, announce
  ``video/x-raw`` caps (RGB or GRAY8 — alpha dropped, the role
  ``videoconvert`` plays in the reference pipelines), push ONE frame.
- ``wavparse``: accumulate until EOS, parse the RIFF container, announce
  ``audio/x-raw`` caps (S16LE/U8/F32LE/S32LE at the file's rate/channels),
  push the sample payload as one buffer (downstream tensor_converter
  re-chunks via frames-per-tensor).
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ..pipeline.caps import Caps, Structure
from ..pipeline.element import CapsEvent, Element, EOSEvent, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..utils.mediadec import decode_png, decode_pnm, parse_wav


class _AccumulatingDecoder(Element):
    """Shared base: buffer bytes until EOS, then decode-and-push."""

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._chunks: list = []

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")
        self.add_src_pad(Caps.any(), "src")

    def start(self):
        self._chunks = []

    def set_caps(self, pad, caps):
        pass  # output caps depend on the decoded header; announced at EOS

    def chain(self, pad, buf):
        for i in range(buf.num_tensors):
            self._chunks.append(
                np.ascontiguousarray(buf.np(i)).tobytes())
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            data = b"".join(self._chunks)
            self._chunks = []
            if data:
                self._decode_and_push(data)
            self.src_pad.push_event(EOSEvent())
            return True
        return super().on_event(pad, event)

    def _decode_and_push(self, data: bytes) -> None:
        raise NotImplementedError


def _push_image(el: _AccumulatingDecoder, img: np.ndarray) -> None:
    h, w, ch = img.shape
    fmt = "GRAY8" if ch == 1 else "RGB"
    el.src_pad.push_event(CapsEvent(Caps([Structure("video/x-raw", {
        "format": fmt, "width": w, "height": h,
        "framerate": Fraction(0, 1)})])))
    el.push(TensorBuffer(tensors=[img], pts=0))


@register_element
class PngDec(_AccumulatingDecoder):
    """``pngdec``: PNG byte stream → one video/x-raw frame."""

    FACTORY = "pngdec"
    PROPERTIES = {}

    def _decode_and_push(self, data: bytes) -> None:
        _push_image(self, decode_png(data))


@register_element
class PnmDec(_AccumulatingDecoder):
    """``pnmdec``: binary PGM/PPM byte stream → one video/x-raw frame."""

    FACTORY = "pnmdec"
    PROPERTIES = {}

    def _decode_and_push(self, data: bytes) -> None:
        _push_image(self, decode_pnm(data))


_WAV_FORMATS = {np.dtype(np.int16): "S16LE", np.dtype(np.uint8): "U8",
                np.dtype(np.float32): "F32LE", np.dtype(np.int32): "S32LE"}


@register_element
class WavParse(_AccumulatingDecoder):
    """``wavparse``: RIFF/WAVE byte stream → audio/x-raw samples."""

    FACTORY = "wavparse"
    PROPERTIES = {}

    def _decode_and_push(self, data: bytes) -> None:
        samples, rate = parse_wav(data)
        self.src_pad.push_event(CapsEvent(Caps([Structure("audio/x-raw", {
            "format": _WAV_FORMATS[samples.dtype],
            "channels": samples.shape[1], "rate": rate})])))
        self.push(TensorBuffer(tensors=[samples], pts=0))
