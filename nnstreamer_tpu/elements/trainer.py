"""tensor_trainer: on-device training driven by the stream.

Parity with gst/nnstreamer/elements/gsttensor_trainer.c + the trainer ABI
(gst/nnstreamer/include/nnstreamer_plugin_api_trainer.h): a trainer
framework receives every stream frame as a (inputs, labels) sample,
trains, exposes per-epoch stats, and on EOS finishes and saves the model
to ``model-save-path`` (orbax checkpoint here, reference waits on
``training_complete_cond``).

The built-in ``jax`` trainer framework trains a registry model (or the
StreamFormer LM) with Adam on the default device; multi-chip training goes
through nnstreamer_tpu.parallel.make_train_step.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple, Type

import numpy as np

from ..pipeline.element import Element, EOSEvent
from ..pipeline.registry import register_element
from ..tensor.caps_util import tensors_template_caps


class TrainerFramework:
    """Trainer ABI (reference GstTensorTrainerFramework:
    create/destroy/start/push_data + epoch/loss stats)."""

    NAME: str = ""

    def create(self, props: Dict[str, Any]) -> None:
        raise NotImplementedError

    def push_data(self, inputs: List[np.ndarray],
                  labels: List[np.ndarray]) -> None:
        raise NotImplementedError

    def finish(self) -> Dict[str, Any]:
        """Complete training; return summary stats (epochs, final loss)."""
        raise NotImplementedError

    def save(self, path: str) -> None:
        raise NotImplementedError

    def destroy(self) -> None:
        pass


def _save_orbax(params, path: str) -> None:
    """Shared checkpoint writer for trainer frameworks."""
    import os

    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    ckpt.save(os.path.abspath(path), params)
    ckpt.wait_until_finished()


_TRAINERS: Dict[str, Type[TrainerFramework]] = {}


def register_trainer(cls: Type[TrainerFramework]) -> Type[TrainerFramework]:
    _TRAINERS[cls.NAME] = cls
    return cls


def find_trainer(name: str) -> Type[TrainerFramework]:
    if name not in _TRAINERS:
        raise KeyError(f"unknown trainer {name!r}; known: {sorted(_TRAINERS)}")
    return _TRAINERS[name]


@register_trainer
class JaxTrainer(TrainerFramework):
    """Built-in trainer: MLP/StreamFormer-style supervised steps with Adam.

    props: model=streamformer|mlp, num-epochs, batch-size, lr, plus model
    hyperparams.  Samples accumulate into batches; each full batch = one
    jitted train step on the default device.
    """

    NAME = "jax"

    def create(self, props: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        self.props = props
        self.batch_size = int(props.get("batch-size", 8))
        self.epochs = int(props.get("num-epochs", 1))
        self.lr = float(props.get("lr", 1e-3))
        self._samples: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []
        self.losses: List[float] = []
        self._state = None
        self._step_fn = None

    def push_data(self, inputs, labels) -> None:
        self._samples.append((inputs, labels))

    @staticmethod
    def _stack(samples):
        """(N, in_dim), (N, out_dim) float32 arrays from sample pairs —
        one stacker for the training AND validation paths."""
        xs = np.stack([np.asarray(s[0][0], np.float32).reshape(-1)
                       for s in samples])
        ys = np.stack([np.asarray(s[1][0], np.float32).reshape(-1)
                       for s in samples])
        return xs, ys

    @staticmethod
    def _loss(p, x, y):
        """THE objective — training grads and the validation metric
        must never diverge, so both call this."""
        import jax
        import jax.numpy as jnp

        h = jax.nn.relu(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(logp * y, axis=-1))

    def _build(self, in_dim: int, out_dim: int):
        import jax
        import jax.numpy as jnp

        hidden = int(self.props.get("hidden", 128))
        k0, k1 = jax.random.split(jax.random.PRNGKey(0))
        params = {
            "w1": jax.random.normal(k0, (in_dim, hidden), jnp.float32) * 0.05,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k1, (hidden, out_dim), jnp.float32) * 0.05,
            "b2": jnp.zeros((out_dim,)),
        }
        opt = {"m": jax.tree.map(jnp.zeros_like, params),
               "v": jax.tree.map(jnp.zeros_like, params),
               "t": jnp.zeros((), jnp.int32)}
        lr = self.lr
        loss_fn = self._loss

        @jax.jit
        def step(p, o, x, y):
            loss, g = jax.value_and_grad(loss_fn)(p, x, y)
            t = o["t"] + 1
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree.map(lambda mm, gg: b1 * mm + (1 - b1) * gg,
                             o["m"], g)
            v = jax.tree.map(lambda vv, gg: b2 * vv + (1 - b2) * gg * gg,
                             o["v"], g)
            tf = t.astype(jnp.float32)
            corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
            p = jax.tree.map(
                lambda pp, mm, vv: pp - lr * corr * mm / (jnp.sqrt(vv) + eps),
                p, m, v)
            return p, {"m": m, "v": v, "t": t}, loss

        self._state = (params, opt)
        self._step_fn = step

    def finish(self) -> Dict[str, Any]:
        import numpy as np

        if not self._samples:
            return {"epochs": 0, "samples": 0, "final_loss": None}
        xs, ys = self._stack(self._samples)
        if self._step_fn is None:
            self._build(xs.shape[1], ys.shape[1])
        params, opt = self._state
        n = len(xs)
        bs = min(self.batch_size, n)
        for _ in range(self.epochs):
            for i in range(0, n - bs + 1, bs):
                params, opt, loss = self._step_fn(
                    params, opt, xs[i:i + bs], ys[i:i + bs])
                self.losses.append(float(loss))
        self._state = (params, opt)
        return {"epochs": self.epochs, "samples": n,
                "final_loss": self.losses[-1] if self.losses else None}

    def evaluate(self, val_data) -> float:
        """Mean loss over held-out (inputs, labels) pairs (the element's
        num-validation-samples split) with the trained params —
        validation frames never touch the optimizer, and the metric is
        the same _loss the optimizer minimized."""
        import jax.numpy as jnp

        if self._state is None or not val_data:
            return float("nan")
        params, _ = self._state
        xs, ys = self._stack(val_data)
        return float(self._loss(params, jnp.asarray(xs),
                                jnp.asarray(ys)))

    def save(self, path: str) -> None:
        if self._state is None:
            return  # no samples were seen; nothing to save
        _save_orbax(self._state[0], path)


class _MeshStreamTrainer(TrainerFramework):
    """Shared skeleton for mesh-jitted stream trainers: accumulate
    (inputs, labels) samples, lazily build the sharded step at first
    finish, run the epoch loop (host-side convert once; device_put per
    step — bounded HBM beats saving a transfer per epoch for a trainer
    fed by an arbitrarily long stream), checkpoint params via orbax.

    Subclasses provide ``_build()`` (set ``self._mesh``, ``self._step``,
    ``self._params``, ``self._opt``, ``self._sharding``),
    ``_host_convert(inputs, labels)`` and optionally ``_summary_extra``.
    """

    def create(self, props: Dict[str, Any]) -> None:
        self.props = props
        self.epochs = int(props.get("num-epochs", 1))
        self._samples: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []
        self.losses: List[float] = []
        self._built = False

    def push_data(self, inputs, labels) -> None:
        self._samples.append((inputs, labels))

    def _build(self) -> None:
        raise NotImplementedError

    def _host_convert(self, inputs, labels):
        raise NotImplementedError

    def _summary_extra(self) -> Dict[str, Any]:
        return {}

    def finish(self) -> Dict[str, Any]:
        import jax

        from ..parallel import mesh_info

        if not self._samples:
            return {"epochs": 0, "samples": 0, "final_loss": None}
        if not self._built:
            self._build()
        host = [self._host_convert(i, l) for i, l in self._samples]
        put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        for _ in range(self.epochs):
            for ins, labs in host:
                self._params, self._opt, loss = self._step(
                    self._params, self._opt, put(ins), put(labs))
                self.losses.append(float(loss))
        return {"epochs": self.epochs, "samples": len(self._samples),
                "final_loss": self.losses[-1] if self.losses else None,
                "mesh": mesh_info(self._mesh), **self._summary_extra()}

    def save(self, path: str) -> None:
        if not self._built:
            return
        _save_orbax(self._params, path)


@register_trainer
class MeshTrainer(_MeshStreamTrainer):
    """``framework=mesh``: the stream trains the SHARDED StreamFormer —
    every (tokens, labels) frame becomes one step of
    :func:`nnstreamer_tpu.parallel.make_train_step` jitted over a
    dp/sp/tp/ep mesh.  This is the pipeline-to-parallel-core bridge:
    the reference's trainer ABI (nnstreamer_plugin_api_trainer.h) only
    ever trains on the host; here the same element drives multi-chip
    SPMD training with ring/Ulysses sequence parallelism and the Pallas
    flash kernel on TPU.

    props (via ``custom=``): mesh axes ``dp/sp/tp/ep`` (defaults:
    auto-factorized over all devices), model hyperparams ``vocab/dim/
    heads/head_dim/mlp/layers/experts/max_seq``, ``seq_parallel``
    (ring|ulysses).  Samples: tensor 0 = tokens (B, T) int32, tensor 1 =
    labels (B, T) int32, already sharded (dp, sp) by the step.
    """

    NAME = "mesh"

    def _build(self) -> None:
        from ..parallel import make_data_sharding, make_mesh
        from ..parallel.train_step import (StreamFormerConfig,
                                           make_train_step)

        p = self.props
        axes = {a: int(p[a]) for a in ("dp", "sp", "tp", "ep") if a in p}
        self._mesh = make_mesh(axis_sizes=axes or None)
        cfg_kw = {k: int(p[k]) for k in ("vocab", "dim", "heads",
                                         "head_dim", "mlp", "layers",
                                         "experts", "max_seq") if k in p}
        for k in ("lr", "capacity_factor", "aux_coef"):
            if k in p:
                cfg_kw[k] = float(p[k])
        if "seq_parallel" in p:
            cfg_kw["seq_parallel"] = str(p["seq_parallel"])
        cfg = StreamFormerConfig(**cfg_kw)
        self._step, self._params, self._opt, _ = make_train_step(
            self._mesh, cfg, seed=int(p.get("seed", 0)))
        self._sharding = make_data_sharding(self._mesh)
        self._built = True

    def _host_convert(self, inputs, labels):
        return (np.asarray(inputs[0], np.int32),
                np.asarray(labels[0], np.int32))


@register_trainer
class MeshVisionTrainer(_MeshStreamTrainer):
    """``framework=mesh-vision``: the stream trains any REGISTRY VISION
    model data-parallel over a mesh — replicated params, frame batches
    sharded on ``dp``, XLA-inserted gradient psum
    (parallel/vision_train.py).  With ``model:vit`` the trained encoder
    is the Pallas flash-attention path.

    props (via ``custom=``): ``model`` (registry name, default vit),
    ``dp`` (default: all devices), ``lr``, plus any model custom props
    (``dim/depth/heads/patch/input_size/num_classes/seed``…).  Samples:
    tensor 0 = frames (B, H, W, 3) uint8, tensor 1 = labels (B,) int32.
    """

    NAME = "mesh-vision"

    _MODEL_KEYS = ("seed", "num_classes", "input_size", "patch", "dim",
                   "depth", "heads", "dtype", "attn", "width")

    def _build(self) -> None:
        import jax

        from ..models.registry import get_model
        from ..parallel import make_mesh
        from ..parallel.vision_train import make_vision_train_step

        p = self.props
        dp = int(p.get("dp", len(jax.devices())))
        self._mesh = make_mesh(n_devices=dp, axis_sizes={"dp": dp})
        model_props = {k: str(p[k]) for k in self._MODEL_KEYS if k in p}
        self._model = get_model(str(p.get("model", "vit")), model_props)
        (self._step, self._params, self._opt,
         self._sharding) = make_vision_train_step(
            self._mesh, self._model, lr=float(p.get("lr", 1e-3)))
        self._dp = dp
        self._built = True

    def _host_convert(self, inputs, labels):
        from ..parallel.vision_train import pad_to_multiple

        return (pad_to_multiple(np.asarray(inputs[0], np.uint8), self._dp),
                pad_to_multiple(np.asarray(labels[0], np.int32)
                                .reshape(-1), self._dp))

    def _summary_extra(self) -> Dict[str, Any]:
        return {"model": self._model.name}


@register_element
class TensorTrainer(Element):
    FACTORY = "tensor_trainer"
    PROPERTIES = {
        "framework": ("jax", "trainer framework name"),
        "model-save-path": (None, "checkpoint path written at EOS"),
        "model-config": (None, "framework model-config path (reference "
                               "property; forwarded to the trainer's "
                               "props)"),
        "num-inputs": (1, "tensors per frame that are inputs"),
        "num-labels": (1, "tensors per frame that are labels"),
        "num-epochs": (1, ""),
        "batch-size": (8, ""),
        "lr": (1e-3, ""),
        "num-training-samples": (0, "frames used for TRAINING; the "
                                    "stream's next num-validation-"
                                    "samples frames are validation "
                                    "(reference gsttensor_trainer "
                                    "split; 0 = train on everything)"),
        "num-validation-samples": (0, "frames after the training split "
                                      "held out for validation loss"),
        "custom": (None, "extra key:value props"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")
        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        from ..filter.framework import FilterProperties

        cls = find_trainer(str(self.framework))
        self.trainer = cls()
        props = {"num-epochs": self.num_epochs, "batch-size": self.batch_size,
                 "lr": self.lr}
        if self.model_config not in (None, ""):
            props["model-config"] = str(self.model_config)
        props.update(FilterProperties.parse_custom(self.custom))
        self.trainer.create(props)
        self.summary: Optional[Dict[str, Any]] = None
        self._done = threading.Event()
        self._n_seen = 0
        self._n_train = int(self.num_training_samples or 0)
        self._n_valid = int(self.num_validation_samples or 0)
        if self._n_valid > 0 and self._n_train <= 0:
            # silently training on everything would withhold the
            # promised validation loss
            raise ValueError(f"{self.name}: num-validation-samples "
                             "needs num-training-samples")
        self._val_data: List = []

    def set_caps(self, pad, caps):
        super().set_caps(pad, caps)  # passthrough

    def chain(self, pad, buf):
        ni = int(self.num_inputs)
        nl = int(self.num_labels)
        if buf.num_tensors < ni + nl:
            raise ValueError(
                f"{self.name}: frame has {buf.num_tensors} tensors, need "
                f"{ni}+{nl}")
        inputs = [buf.np(i) for i in range(ni)]
        labels = [buf.np(ni + i) for i in range(nl)]
        # reference split semantics (gsttensor_trainer push_data): the
        # first num-training-samples frames train, the NEXT
        # num-validation-samples are held out, anything beyond both is
        # ignored; with no split configured everything trains
        idx = self._n_seen
        self._n_seen += 1
        if self._n_train <= 0:
            self.trainer.push_data(inputs, labels)
        elif idx < self._n_train:
            self.trainer.push_data(inputs, labels)
        elif idx < self._n_train + self._n_valid:
            self._val_data.append((inputs, labels))
        return self.push(buf)

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            # train + save before propagating EOS (reference blocks on
            # training_complete_cond at EOS)
            self.summary = self.trainer.finish()
            if self._val_data:
                self.summary["validation_samples"] = len(self._val_data)
                evaluate = getattr(self.trainer, "evaluate", None)
                if callable(evaluate):
                    self.summary["validation_loss"] = float(
                        evaluate(self._val_data))
                self._val_data = []    # release the held-out frames
            if self.model_save_path:
                self.trainer.save(str(self.model_save_path))
            self._done.set()
        super().on_event(pad, event)

    def wait_done(self, timeout=None) -> bool:
        return self._done.wait(timeout)
