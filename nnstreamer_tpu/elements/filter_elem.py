"""tensor_filter: THE inference element.

Parity with gst/nnstreamer/tensor_filter/tensor_filter.c (+ the shared
property/lifecycle logic of tensor_filter_common.c):

- properties: framework (incl. ``auto``), model, forced input/output
  dims/types, accelerator string, custom properties, input-combination /
  output-combination, latency/throughput readouts, shared key, is-updatable
  (reference property table tensor_filter_common.c)
- start() opens the backend (reference :1492-1504 → open_fw :2420)
- caps: sink accepts static tensors; src caps derived from model output info
  (reference transform_caps/configure :902-1280), with per-buffer
  validation in the hot loop (:557-626)
- hot loop (reference transform :631-894): validate → input-combination →
  invoke → output-combination/wrap → push, keeping device arrays unsynced
- model-update custom event (``tensor_filter_update_model``) triggers
  backend reload (reference :1413-1446)
"""

from __future__ import annotations

from typing import List, Optional

from ..filter.framework import (Accelerator, FilterError, FilterProperties,
                                close_backend, open_backend)
from ..pipeline.element import (CustomEvent, Element, FlowReturn,
                                LoweredStep, QoSEvent)
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import caps_from_config, static_tensors_caps
from ..tensor.info import TensorsConfig, TensorsInfo


def _parse_combination(s) -> Optional[List[int]]:
    if s in (None, ""):
        return None
    return [int(x) for x in str(s).split(",")]


class CrossStreamBatcher:
    """Bucket/dispatch core of the ``batch-timeout-ms`` coalescer.

    Extracted from :class:`TensorFilter`'s micro-batch discipline so the
    query serving plane reuses the exact same rules for CROSS-STREAM
    continuous batching (``query/server.py``): a collecting bucket of
    opaque items dispatches when it FILLS (``add`` returns True) or when
    the earliest resident deadline expires.  Deadlines are PER ITEM —
    each ``add`` may carry its own residency budget (the QoS lever:
    ``query/overload.py bucket_budget`` gives gold a quarter of the
    configured timeout, so a gold frame landing in a bucket that bronze
    traffic opened pulls the dispatch deadline in) — and the bucket's
    effective deadline is the minimum over residents.

    Threadless by design: the owner supplies the waiting and the
    dispatch.  ``tensor_filter`` pairs it with its deadline-watcher
    thread (push-style producers); ``tensor_query_serversrc`` drives it
    from its own source thread's blocking collect loop (pull-style).
    Not itself thread-safe — callers serialize ``add``/``take`` under
    their own coalesce lock where producers and watchers race.
    """

    __slots__ = ("capacity", "timeout_s", "items", "_t0", "_deadline",
                 "_clock")

    def __init__(self, capacity: int, timeout_s: float = 0.0,
                 clock=None) -> None:
        import time as _time

        self.capacity = max(1, int(capacity))
        self.timeout_s = max(0.0, float(timeout_s))
        self._clock = clock if clock is not None else _time.monotonic
        self.items: list = []
        self._t0: Optional[float] = None       # arrival of oldest item
        self._deadline: Optional[float] = None  # min(arrival + budget)

    @property
    def fill(self) -> int:
        return len(self.items)

    def full(self) -> bool:
        return len(self.items) >= self.capacity

    def opened_at(self) -> Optional[float]:
        """Arrival time of the oldest resident item (None when empty)."""
        return self._t0

    def deadline(self) -> Optional[float]:
        """Absolute dispatch deadline (None when empty)."""
        return self._deadline if self.items else None

    def add(self, item, budget_s: Optional[float] = None) -> bool:
        """Append one item; returns True when the bucket is now full
        (caller must dispatch).  ``budget_s`` overrides the bucket-wide
        ``timeout_s`` for this item's residency deadline."""
        now = self._clock()
        if not self.items:
            self._t0 = now
        budget = self.timeout_s if budget_s is None else max(0.0, budget_s)
        deadline = now + budget
        if self._deadline is None or deadline < self._deadline:
            self._deadline = deadline
        self.items.append(item)
        return len(self.items) >= self.capacity

    def expired(self, now: Optional[float] = None) -> bool:
        """True when a resident item's budget has run out (caller must
        dispatch the partial bucket)."""
        if not self.items or self._deadline is None:
            return False
        return (self._clock() if now is None else now) >= self._deadline

    def remaining(self, now: Optional[float] = None) -> float:
        """Seconds until the earliest resident deadline (0 when expired,
        +inf when empty)."""
        if not self.items or self._deadline is None:
            return float("inf")
        return max(0.0, self._deadline
                   - (self._clock() if now is None else now))

    def take(self) -> list:
        """Pop every resident item (bucket order) and reset."""
        items, self.items = self.items, []
        self._t0 = None
        self._deadline = None
        return items


@register_element
class TensorFilter(Element):
    FACTORY = "tensor_filter"
    PROPERTIES = {
        "framework": ("auto", "backend name or auto"),
        "model": (None, "model name/path/object"),
        "input-dim": (None, "forced input dims"),
        "input-type": (None, "forced input types"),
        "output-dim": (None, "forced output dims"),
        "output-type": (None, "forced output types"),
        "accelerator": (None, "e.g. true:tpu"),
        "custom": (None, "key:value,... custom properties"),
        "inputname": (None, "graph input tensor name(s) (reference "
                            "property; merged into custom props)"),
        "outputname": (None, "graph output tensor name(s)"),
        "inputlayout": (None, "reference per-tensor layout hints "
                              "(NHWC/NCHW/ANY/NONE) — accepted and "
                              "forwarded to the backend custom props; "
                              "the XLA path is layout-agnostic (the "
                              "compiler lays tensors out itself)"),
        "outputlayout": (None, "see inputlayout"),
        "inputranks": (None, "reference READABLE property: rank per "
                             "input tensor of the opened model"),
        "outputranks": (None, "reference READABLE property: rank per "
                              "output tensor"),
        "sub-plugins": (None, "reference READABLE property: registered "
                              "filter backends"),
        # "latency"/"throughput" (reference READABLE stats) are python
        # properties on this class — get_property reaches them via
        # getattr, so they must NOT appear here (the defaults loop
        # would try to assign the read-only descriptors)
        "input-combination": (None, "indices of input tensors to feed"),
        "output-combination": (None, "i0,i1/o0,o1 passthrough+output mix"),
        "shared-tensor-filter-key": (None, "share backend across instances"),
        "is-updatable": (False, "allow model-update events"),
        "latency-report": (False, "report invoke latency"),
        "batch": (1, "micro-batch N frames into one device invoke "
                     "(latency/throughput trade; backend-gated)"),
        "batch-timeout-ms": (0.0, "adaptive micro-batch deadline: with "
                                  "batch>1, dispatch the collecting "
                                  "bucket when it FILLS or when the "
                                  "oldest queued frame has waited this "
                                  "long — and flush in-flight results "
                                  "whose frames' budget expired — so "
                                  "one launch line serves both "
                                  "throughput (bucket fills fast, "
                                  "deadline never fires) and latency "
                                  "(underrun dispatches partial "
                                  "buckets).  0 = fixed batching (wait "
                                  "for a full bucket / EOS)"),
        "inflight": (1, "dispatched micro-batches kept in flight before "
                        "the oldest is awaited (pipeline depth).  1 = "
                        "double-buffered (one collecting, one dispatched)"
                        ".  Deeper overlaps K dispatch round-trips — the "
                        "lever when dispatch latency, not device compute,"
                        " bounds throughput (remote/tunneled chips); "
                        "costs K batches of output HBM+latency"),
        "workers": (1, "parallel invoke workers: N>1 spawns a pool that "
                       "consumes frames concurrently (per-worker backend "
                       "instance unless the backend declares "
                       "THREADSAFE_INVOKE) and reassembles results in "
                       "sequence order before pushing downstream.  The "
                       "lever when per-frame invoke latency (CPU model, "
                       "remote call) bounds throughput and the backend "
                       "releases the GIL; composes with per-frame QoS/"
                       "combination properties.  With batch>1 the "
                       "micro-batch+inflight machinery already overlaps "
                       "dispatch, so workers is forced to 1 there"),
        "output-device": (False, "emit device-resident outputs (BatchView/"
                                 "jax.Array payloads): a downstream batched "
                                 "filter consumes them without any host "
                                 "round trip — cascade intermediates never "
                                 "leave HBM.  Host consumers (decoders, "
                                 "sinks) still work: they materialize one "
                                 "d2h per batch on first touch"),
    }

    #: the reference's own property names for the same settings
    #: (gsttensor_filter_common: "input"/"inputtype"/"output"/
    #: "outputtype" set forced dims/types, "inputname"/"outputname"
    #: select graph tensors) — every custom-filter ssat line uses the
    #: short spellings, so they must work verbatim
    REFERENCE_PROP_ALIASES = {
        "input": "input-dim", "inputtype": "input-type",
        "output": "output-dim", "outputtype": "output-type",
    }

    #: reference G_PARAM_READABLE-only properties — a write is an
    #: error there (critical warning), not a silent no-op; enforced by
    #: Element.set_property (aliases never map TO a read-only name, so
    #: mapping first preserves the same behavior)
    READONLY_PROPERTIES = ("sub-plugins", "inputranks", "outputranks",
                           "latency", "throughput")

    def set_property(self, key, value):
        super().set_property(self.REFERENCE_PROP_ALIASES.get(key, key),
                             value)

    def get_property(self, key):
        key = self.REFERENCE_PROP_ALIASES.get(key, key)
        if key in ("sub-plugins", "sub_plugins"):
            from ..filter.framework import list_filters

            return ",".join(list_filters())   # registry is sorted
        if key in ("inputranks", "outputranks"):
            fw = getattr(self, "fw", None)
            if fw is None:
                return ""
            in_info, out_info = fw.get_model_info()
            info = in_info if key == "inputranks" else out_info
            return ",".join(str(len(t.dims)) for t in info)
        return super().get_property(key)

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")
        self.add_src_pad(static_tensors_caps(), "src")

    def static_check(self):
        """Pre-play verifier hook: surface the scheduler decisions
        ``start()`` would make silently (forced workers=1, ignored
        inflight/deadline) and the configs it would reject outright
        (mesh without micro-batching) — same rules, before any thread
        exists."""
        out = []

        def _num(key):
            raw = self.get_property(key)
            if raw in (None, ""):
                return 1
            try:
                return int(raw)
            except (TypeError, ValueError):
                # start()'s int() would raise: a genuine reject
                out.append(("error", f"{self.name}: {key}={raw!r} is not "
                                     "an integer"))
                return 1

        batch = _num("batch")
        workers = _num("workers")
        inflight = _num("inflight")
        if batch < 1 or workers < 1 or inflight < 1:
            # start() clamps with max(1, ...): the pipeline runs, the
            # value is silently overridden — report, don't reject
            out.append(("warning",
                        f"{self.name}: batch/workers/inflight below 1 "
                        f"(got {batch}/{workers}/{inflight}) is clamped "
                        "to 1 at start"))
        batch, workers, inflight = (max(1, batch), max(1, workers),
                                    max(1, inflight))
        if workers > 1 and batch > 1:
            out.append(("warning",
                        f"{self.name}: workers={workers} with "
                        f"batch={batch}: micro-batching already overlaps "
                        "dispatch (use inflight=); the scheduler forces "
                        "workers=1"))
        if inflight > 1 and batch <= 1:
            out.append(("warning",
                        f"{self.name}: inflight={inflight} needs "
                        "micro-batching (batch>1); runs per-frame"))
        try:
            deadline = float(self.batch_timeout_ms or 0)
        except (TypeError, ValueError):
            deadline = 0
            out.append(("error", f"{self.name}: batch-timeout-ms="
                                 f"{self.batch_timeout_ms!r} is not a "
                                 "number"))
        if deadline > 0 and batch <= 1:
            out.append(("warning",
                        f"{self.name}: batch-timeout-ms needs "
                        "micro-batching (batch>1); ignored"))
        if workers > 1 and self.shared_tensor_filter_key:
            out.append(("warning",
                        f"{self.name}: workers={workers} with "
                        "shared-tensor-filter-key may force workers=1 "
                        "(per-worker instances impossible unless the "
                        "backend declares THREADSAFE_INVOKE)"))
        if "mesh:" in str(self.custom or "") and batch <= 1:
            out.append(("error",
                        f"{self.name}: custom=mesh:... requires "
                        "micro-batching (set batch= to a multiple of "
                        "dp); per-frame dispatch cannot shard"))
        pl = self.pipeline
        if (pl is not None and getattr(pl, "fuse", False)
                and (workers > 1 or batch > 1)):
            out.append(("info",
                        f"{self.name}: workers/batch push from their own "
                        "threads, so this element opts out of fused "
                        "dispatch (the segment splits here)"))
        return out

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        in_info = out_info = None
        if self.input_dim and self.input_type:
            in_info = TensorsInfo.from_strings(str(self.input_dim),
                                               str(self.input_type))
        if self.output_dim and self.output_type:
            out_info = TensorsInfo.from_strings(str(self.output_dim),
                                                str(self.output_type))
        custom = FilterProperties.parse_custom(self.custom)
        # "inputname=data" / "outputname=prob" (and the layout hints)
        # are first-class reference properties; backends read them from
        # the custom map
        for key in ("inputname", "outputname", "inputlayout",
                    "outputlayout"):
            val = getattr(self, key, None)
            if val not in (None, "") and key not in custom:
                custom[key] = str(val)
        props = FilterProperties(
            framework=str(self.framework or "auto"), model=self.model,
            input_info=in_info, output_info=out_info,
            accelerators=Accelerator.parse(self.accelerator),
            custom_properties=custom,
            shared_key=self.shared_tensor_filter_key)
        self.fw = open_backend(props)
        self._props = props
        self.stats = getattr(self.fw, "stats", None)
        self._in_comb = _parse_combination(self.input_combination)
        self._throttle_ns = 0          # QoS-driven drop interval
        self._last_kept_pts: Optional[int] = None
        self.dropped = 0               # frames throttle-dropped
        self._out_comb = None
        if self.output_combination not in (None, ""):
            ins, _, outs = str(self.output_combination).partition("/")
            self._out_comb = (_parse_combination(ins) or [],
                              _parse_combination(outs) or [])
        # micro-batching state (double-buffered: one batch collecting, one
        # dispatched-in-flight — see FilterFramework.invoke_batched)
        # batch=0 and unset both mean "no micro-batching" (the max()
        # clamp folds them) # nnslint: allow(falsy-zero-default)
        self._batch = max(1, int(self.batch or 1))
        if self._batch > 1 and not getattr(self.fw, "SUPPORTS_BATCHING",
                                           False):
            self._batch = 1
        self._emit_device = bool(self.output_device)
        if self._emit_device and not getattr(self.fw, "SUPPORTS_BATCHING",
                                             False):
            from ..utils.log import ml_logw

            ml_logw("%s: output-device requested but backend %s has no "
                    "device execution engine; emitting host tensors",
                    self.name, self._props.framework)
            self._emit_device = False
        if self._batch <= 1 and getattr(self.fw, "_mesh", None) is not None:
            # only the BATCHED executable spans the mesh; per-frame
            # dispatch would silently serve on one device while paying
            # replicated-param HBM on all of them
            from ..filter.framework import FilterError

            raise FilterError(
                f"{self.name}: custom=mesh:dp=N requires micro-batching "
                f"(set batch= to a multiple of dp); per-frame dispatch "
                "cannot shard")
        # collecting bucket of (tensors, buf) pairs — the shared
        # bucket/dispatch core (also driven by the query serving
        # plane's cross-stream batcher)
        self._bucket = CrossStreamBatcher(
            self._batch, max(0.0, float(self.batch_timeout_ms or 0)) / 1e3)
        # cross-stream batch accounting: invokes/frames served through
        # pre-batched buffers (query/server.py buckets) — feeds the
        # nns_mfu frame-rate math, which would otherwise undercount a
        # bucket of n frames as one
        self._xb_invokes = 0
        self._xb_frames = 0
        self._xb_warm = 0      # capacity whose pad shapes are compiled
        # FIFO of dispatched (bufs, handle, t0) batches; stream order is
        # the queue order.  Depth 1 keeps the historical double-buffering
        # (one collecting + one dispatched)
        from collections import deque

        self._inflight: deque = deque()
        # inflight=0 and unset both mean depth 1 (max() clamp)
        # nnslint: allow(falsy-zero-default)
        self._inflight_depth = max(1, int(self.inflight or 1))
        if self._inflight_depth > 1 and self._batch <= 1:
            from ..utils.log import ml_logw

            ml_logw("%s: inflight=%d needs micro-batching (batch>1); "
                    "running per-frame", self.name, self._inflight_depth)
            self._inflight_depth = 1
        self._rewarm = False            # re-compile owed after pushdown
        self._pushdown = None           # fn of a fused device reduction
        # adaptive micro-batching: a deadline-driven coalescer.  With
        # batch-timeout-ms set, a partial bucket no longer waits for the
        # stream to fill it — the watcher thread dispatches it (and
        # flushes expired in-flight results) once the OLDEST queued
        # frame's latency budget runs out, so throughput configs and
        # latency configs share one launch line.
        self._batch_deadline = max(0.0,
                                   float(self.batch_timeout_ms or 0)) / 1e3
        if self._batch_deadline > 0 and self._batch <= 1:
            from ..utils.log import ml_logw

            ml_logw("%s: batch-timeout-ms needs micro-batching (batch>1);"
                    " ignored", self.name)
            self._batch_deadline = 0.0
        self._bucket.timeout_s = self._batch_deadline
        import threading

        from ..analysis.sanitizer import make_lock

        self._coalesce_lock = make_lock("filter.coalesce")
        self._deadline_stop = threading.Event()
        self._deadline_thread = None
        # parallel invoke workers: a pool of N invoke threads fed from
        # chain(), with a dedicated pusher reassembling results in strict
        # sequence order before pushing downstream.  Orthogonal to the
        # micro-batch machinery: batch>1 already overlaps dispatch via
        # inflight, so workers collapses to 1 there.
        # workers=0 and unset both mean no pool (max() clamp)
        # nnslint: allow(falsy-zero-default)
        self._workers_n = max(1, int(self.workers or 1))
        if self._workers_n > 1 and self._batch > 1:
            from ..utils.log import ml_logw

            ml_logw("%s: workers=%d with batch>1: micro-batching already "
                    "overlaps dispatch (use inflight=); running workers=1",
                    self.name, self._workers_n)
            self._workers_n = 1
        thread_safe = bool(getattr(type(self.fw), "THREADSAFE_INVOKE",
                                   False))
        if self._workers_n > 1 and props.shared_key and not thread_safe:
            from ..utils.log import ml_logw

            ml_logw("%s: workers=%d needs per-worker backend instances, "
                    "which shared-tensor-filter-key forbids (backend not "
                    "THREADSAFE_INVOKE); running workers=1",
                    self.name, self._workers_n)
            self._workers_n = 1
        if self._workers_n > 1:
            self._start_workers(thread_safe)
        if self._batch > 1:
            self.fw.warmup_batched(self._batch)
        if self._batch_deadline > 0:
            self._deadline_thread = threading.Thread(
                target=self._deadline_loop, daemon=True,
                name=f"batch-deadline:{self.name}")
            self._deadline_thread.start()
        # scheduler-state gauges, evaluated only at /metrics scrape time
        # (obs/metrics.py lazy-callable contract: zero per-frame cost);
        # pipeline-labeled + identity-unregistered so concurrent
        # pipelines with same-named filters don't fight over keys
        from ..obs.metrics import REGISTRY, Gauge

        labels = {"element": self.name,
                  "pipeline": getattr(self.pipeline, "name", "") or ""}
        self._obs_gauges = [REGISTRY.register(Gauge(n, labels, fn=f))
                            for n, f in (
            ("nns_filter_batch_size", lambda: self._batch),
            ("nns_filter_inflight", lambda: len(self._inflight)),
            ("nns_filter_pending", lambda: self._bucket.fill),
            ("nns_filter_dropped", lambda: self.dropped),
            # cross-stream (pre-batched) traffic: shared invokes and the
            # frames they served — batched-vs-solo evidence for the
            # profiler (query/server.py bucket dispatch counters are the
            # serving-plane side of the same story)
            ("nns_filter_xbatch_invokes", lambda: self._xb_invokes),
            ("nns_filter_xbatch_frames", lambda: self._xb_frames))]
        self._register_device_gauges(labels)

    def _register_device_gauges(self, labels) -> None:
        """Device accounting for the jit-exec backend family: live
        ``nns_mfu`` (achieved FLOP/s over the chip peak — the SAME
        formula and peak tables as bench.py's mfu_stream, so the gauge
        and the BENCH rows cannot disagree), achieved HBM bytes/s, and
        device memory in use.  All lazy callables: the FLOPs/bytes cost
        model (XLA cost analysis over the negotiated shapes) is
        computed once at the first scrape that wants it, through the
        backend's already-warm executable cache — zero per-frame cost,
        no compile on the open path."""
        fw = self.fw
        if getattr(fw, "_jitted", None) is None:
            return   # not a jit-exec backend: no cost model, no claim
        from ..obs.attrib import device_peaks, estimate_jit_cost
        from ..obs.metrics import REGISTRY, Gauge

        el = self

        def _make_rate():
            # scrape-to-scrape frame rate (first scrape: lifetime).
            # One state box per gauge so nns_mfu and bytes/s sampled in
            # the same scrape each get a real window.
            state = {"frames": None, "t": None}

            def _frame_rate() -> float:
                import time as _time

                st = getattr(fw, "stats", None)
                if st is None:
                    return 0.0
                # frames ~= invokes x micro-batch (batched dispatch
                # records one stat per bucket; exact at batch=1).
                # Cross-stream buckets record one stat per shared
                # invoke but serve a VARIABLE fill — count their real
                # frames, or the MFU of a batching server understates
                # by the fill factor
                frames = ((st.total_invokes - el._xb_invokes)
                          * max(1, el._batch) + el._xb_frames)
                now = _time.monotonic()
                prev_f, prev_t = state["frames"], state["t"]
                state["frames"], state["t"] = frames, now
                if prev_t is None or now - prev_t < 0.05:
                    return st.throughput * max(1, el._batch)
                return max(0.0, (frames - prev_f) / (now - prev_t))

            return _frame_rate

        mfu_rate, bw_rate = _make_rate(), _make_rate()

        def _mfu() -> float:
            flops, _ = estimate_jit_cost(fw)
            peak, _ = device_peaks(fw._device)
            if not flops or not peak:
                return 0.0
            return mfu_rate() * flops / peak

        def _bytes_per_s() -> float:
            _, nbytes = estimate_jit_cost(fw)
            return bw_rate() * nbytes if nbytes else 0.0

        def _mem_bytes() -> float:
            stats_fn = getattr(fw._device, "memory_stats", None)
            if stats_fn is None:
                return 0.0
            stats = stats_fn() or {}
            return float(stats.get("bytes_in_use", 0))

        dev = dict(labels)
        dev["device"] = str(getattr(fw._device, "device_kind", "")
                            or getattr(fw._device, "platform", ""))
        self._obs_gauges.extend(
            REGISTRY.register(Gauge(n, dev, fn=f)) for n, f in (
                ("nns_mfu", _mfu),
                ("nns_device_bytes_per_s", _bytes_per_s),
                ("nns_device_mem_bytes", _mem_bytes)))

    def stop(self):
        from ..obs.metrics import REGISTRY

        for gauge in getattr(self, "_obs_gauges", ()):
            REGISTRY.unregister(gauge)
        self._obs_gauges = []
        self._deadline_stop.set()
        if self._deadline_thread is not None:
            self._deadline_thread.join(timeout=10)
            self._deadline_thread = None
        self._stop_workers()
        close_backend(getattr(self, "fw", None), self._props)
        self.fw = None

    # -- negotiation ---------------------------------------------------------
    def set_caps(self, pad, caps):
        from ..tensor.caps_util import config_from_caps

        self._drain_batches()   # renegotiation must not reorder frames
        self._drain_workers()
        in_cfg = config_from_caps(caps)
        model_in, model_out = self.fw.get_model_info()
        expect = model_in
        if self._in_comb is not None:
            selected = in_cfg.info
            expect_sel = TensorsInfo([in_cfg.info[i] for i in self._in_comb])
            if not expect_sel.is_equal(model_in):
                raise ValueError(
                    f"{self.name}: input-combination {self._in_comb} gives "
                    f"{expect_sel}, model wants {model_in}")
        elif not in_cfg.info.is_equal(expect):
            # try dynamic renegotiation (reference SET_INPUT_INFO path)
            try:
                _, model_out = self.fw.set_input_info(in_cfg.info)
            except FilterError:
                raise ValueError(
                    f"{self.name}: incoming {in_cfg.info} != model "
                    f"input {expect}") from None
            # per-worker backend instances serve the same stream: they
            # must renegotiate too, or workers 1..N-1 keep invoking
            # against the stale input config (same propagation the
            # reload_model event path does)
            for wfw in getattr(self, "_wk_backends", []):
                if wfw is not self.fw:
                    wfw.set_input_info(in_cfg.info)
        self._in_config = in_cfg
        out_infos = model_out
        if self._out_comb is not None:
            ins, outs = self._out_comb
            combined = [in_cfg.info[i] for i in ins] + \
                       [model_out[i] for i in outs]
            out_infos = TensorsInfo(combined)
        self._out_config = TensorsConfig(info=out_infos, rate=in_cfg.rate)
        self.announce_src_caps(caps_from_config(self._out_config))

    # -- hot loop ------------------------------------------------------------
    def _preprocess(self, buf: TensorBuffer):
        """QoS throttle-drop + per-buffer validation + input-combination.
        Returns the selected input tensor list, or ``FlowReturn.DROPPED``.
        Shared by interpreted chain, the fused plan step, and the worker
        submit path."""
        # QoS throttle-drop (reference :609): after a downstream QoS event,
        # drop frames arriving faster than the reported consumption rate
        if self._throttle_ns and buf.pts is not None:
            last = self._last_kept_pts
            if last is not None and buf.pts - last < self._throttle_ns:
                self.dropped += 1
                return FlowReturn.DROPPED
            self._last_kept_pts = buf.pts
        elif buf.pts is not None:
            self._last_kept_pts = buf.pts
        # per-buffer validation against negotiated meta (reference :557-626)
        in_info = self._in_config.info
        if buf.num_tensors != in_info.num_tensors:
            raise ValueError(
                f"{self.name}: buffer has {buf.num_tensors} tensors, "
                f"negotiated {in_info.num_tensors}")
        tensors = buf.tensors
        if self._in_comb is not None:
            tensors = [tensors[i] for i in self._in_comb]
        return tensors

    def chain(self, pad, buf: TensorBuffer) -> FlowReturn:
        fw = self.fw
        if fw is None or not fw.opened:
            raise RuntimeError(f"{self.name}: not started")
        if self._rewarm:
            # deferred from the pushdown-fusion event handler (compiling
            # there deadlocks the downstream queue's drain thread): pay
            # both executable compiles here, before the stream is deep,
            # so neither a mid-stream batch nor the EOS flush tail does
            self._rewarm = False
            fw.warmup_batched(self._batch)
        xb = buf.extra.get("nns_xbatch")
        if xb is not None:
            # cross-stream batch (query/server.py bucket): the frames
            # arrive pre-coalesced, stacked along a leading axis — one
            # shared device invoke serves the whole client population.
            # Pre-batched traffic supersedes local micro-batching and
            # the worker pool (it IS the batching).
            return self.push(self._invoke_xbatch(buf, xb))
        tensors = self._preprocess(buf)
        if tensors.__class__ is FlowReturn:
            return tensors
        if self._batch > 1:
            if self._batch_deadline > 0:
                # coalescer path: the deadline watcher dispatches/flushes
                # concurrently, so collection and dispatch serialize on
                # the coalesce lock (stream order is the lock order)
                with self._coalesce_lock:
                    return self._collect_frame(tensors, buf)
            return self._collect_frame(tensors, buf)
        if self._workers_n > 1:
            return self._submit_frame(tensors, buf)
        if self._emit_device:
            outs = fw.invoke(list(tensors), emit_device=True)
        else:
            outs = fw.invoke(list(tensors))
        return self._push_result(buf, outs)

    def plan_step(self):
        """Fused-dispatch hook: the per-frame synchronous path flattens
        into an upstream segment plan; micro-batching and the worker pool
        push from their own threads, so they keep interpreted dispatch."""
        if self._batch > 1 or self._workers_n > 1:
            return None
        return self._plan_invoke

    def lower_reason(self):
        # 0/unset alike collapse to 1 # nnslint: allow(falsy-zero-default)
        if max(1, int(self.batch or 1)) > 1:
            return "batch>1: the micro-batch coalescer owns dispatch"
        # 0/unset alike collapse to 1 # nnslint: allow(falsy-zero-default)
        if max(1, int(self.workers or 1)) > 1:
            return "workers>1: the invoke pool owns dispatch"
        fw = getattr(self, "fw", None)
        if fw is not None:
            if getattr(fw, "_forward_fn", None) is None \
                    or getattr(fw, "_params_dev", None) is None \
                    and getattr(fw, "_jitted", None) is None:
                return (f"backend {self._props.framework!r} has no "
                        "jit-exec forward (host-code invoke)")
            if getattr(self, "_throttle_ns", 0):
                return ("QoS throttling active: per-buffer drop state "
                        "is host-side")
        return None

    def lower_step(self):
        """fuse=xla: the jit-exec forward joins the segment's single
        jitted computation — params ride as jit arguments (the
        ``_jitexec`` warm-executable discipline), input/output
        combination is pure index selection, and the PR 9 stacked-bucket
        path is served by the segment compiler's vmapped executable
        (``SegmentExec.run_stacked`` reuses the ``pad_rows``
        padded-bucket policy, so fills never recompile)."""
        if self.lower_reason() is not None \
                or getattr(self, "fw", None) is None \
                or getattr(self, "_in_config", None) is None:
            return None
        fw = self.fw
        fwd = getattr(fw, "_forward_fn", None)
        if fwd is None or not fw.opened:
            return None
        in_comb, out_comb = self._in_comb, self._out_comb

        def fn(params, ts, _fwd=fwd, _in=in_comb, _out=out_comb):
            xs = ts if _in is None else [ts[i] for i in _in]
            outs = list(_fwd(params, *xs))
            if _out is not None:
                ins, sel = _out
                outs = [ts[i] for i in ins] + [outs[k] for k in sel]
            return outs

        return LoweredStep(fn, params=fw._params_dev)

    def _plan_invoke(self, buf: TensorBuffer):
        fw = self.fw
        if fw is None or not fw.opened:
            raise RuntimeError(f"{self.name}: not started")
        xb = buf.extra.get("nns_xbatch")
        if xb is not None:
            # a cross-stream bucket traverses the fused segment as ONE
            # plan execution — the per-frame dispatch tax is paid once
            # per bucket, and the device sees the whole tile
            return self._invoke_xbatch(buf, xb)
        tensors = self._preprocess(buf)
        if tensors.__class__ is FlowReturn:
            return tensors
        if self._emit_device:
            outs = fw.invoke(list(tensors), emit_device=True)
        else:
            outs = fw.invoke(list(tensors))
        return self._compose_output(buf, list(outs))

    def _invoke_xbatch(self, buf: TensorBuffer, xb) -> TensorBuffer:
        """One shared device invoke for a cross-stream batch buffer
        (``buf.extra["nns_xbatch"]``, query/server.py): tensors are
        pre-stacked ``(n, *frame_shape)`` rows from up to ``xb.capacity``
        client streams.  A batching-capable backend dispatches them
        through the padded-bucket executable
        (:meth:`~nnstreamer_tpu.filter.backends._jitexec.JitExecMixin.
        invoke_stacked` — one warm shape regardless of fill); others
        fall back to a row-wise invoke loop (correct, not faster).

        No QoS throttle-drop here: every row is an ADMITTED client
        request — silently dropping one would violate the overload
        plane's every-refusal-is-explicit invariant (a drop would strand
        its client's reply, not shed it)."""
        in_info = self._in_config.info
        if buf.num_tensors != in_info.num_tensors:
            raise ValueError(
                f"{self.name}: batch buffer has {buf.num_tensors} "
                f"tensors, negotiated {in_info.num_tensors}")
        tensors = buf.tensors
        if self._in_comb is not None:
            tensors = [tensors[i] for i in self._in_comb]
        fw = self.fw
        n = xb.n
        pl = self.pipeline
        tracer = pl.tracer if pl is not None else None
        rec = tracer is not None and tracer.ring is not None
        t0 = 0
        if rec:
            import time as _time

            t0 = _time.monotonic_ns()
        if getattr(fw, "SUPPORTS_BATCHING", False) \
                and hasattr(fw, "invoke_stacked"):
            if self._xb_warm != xb.capacity:
                # first bucket (or a capacity change): pre-compile every
                # pad shape NOW, not one compile-stall per shape spread
                # across the serving steady state
                fw.warmup_stacked(xb.capacity)
                self._xb_warm = xb.capacity
            outs = fw.invoke_stacked(list(tensors), n,
                                     capacity=xb.capacity,
                                     emit_device=self._emit_device)
        else:
            import numpy as _np

            rows = [fw.invoke([t[i] for t in tensors]) for i in range(n)]
            outs = [_np.stack([_np.asarray(r[k]) for r in rows])
                    for k in range(len(rows[0]))]
        self._xb_invokes += 1
        self._xb_frames += n
        if rec:
            import time as _time

            t1 = _time.monotonic_ns()
            # the SHARED dispatch window, once per resident client trace:
            # each client's merged timeline shows its frame inside the
            # same device-invoke span its bucket peers overlap
            # (obs/attrib.py — per-frame wall-clock truth, not a 1/n
            # share).  The materialization sync point (TensorBuffer.np
            # at the reply split) extends this with the real device time.
            seq = buf.extra.get("nns_seq", -1)
            for extra in xb.extras:
                ctx = extra.get("nns_trace")
                if ctx is not None and ctx.trace_id:
                    tracer.annotate_span("device-invoke", t0, t1,
                                         seq=seq, trace_id=ctx.trace_id)
        return self._compose_output(buf, list(outs))

    def _compose_output(self, buf: TensorBuffer, outs) -> TensorBuffer:
        out_tensors = outs
        if self._out_comb is not None:
            ins, sel = self._out_comb
            out_tensors = [buf.tensors[i] for i in ins] + \
                          [outs[i] for i in sel]
        return buf.with_tensors(out_tensors)

    def _push_result(self, buf: TensorBuffer, outs) -> FlowReturn:
        return self.push(self._compose_output(buf, outs))

    # -- parallel invoke workers ---------------------------------------------
    def _start_workers(self, thread_safe: bool) -> None:
        """Spawn the invoke pool + ordered pusher.  Where the backend is
        not thread-safe each worker gets its OWN backend instance (same
        props, so same model/weights); a THREADSAFE_INVOKE backend (e.g.
        the jit-executable family — concurrent jax dispatch is supported)
        is shared, so compiled executables and device params exist once."""
        import queue as _q
        import threading

        from ..filter.framework import open_backend

        backends = []
        for i in range(self._workers_n):
            if thread_safe or i == 0:
                backends.append(self.fw)
            else:
                import dataclasses as _dc

                backends.append(open_backend(_dc.replace(self._props)))
        self._wk_backends = backends
        from ..analysis.sanitizer import make_condition

        self._wk_tasks: _q.Queue = _q.Queue()
        self._wk_cv = make_condition("filter.workers")
        self._wk_results: dict = {}   # seq -> (buf, outs, exc, ready_ns)
        self._wk_seq = 0                # frames submitted
        self._wk_pushed = 0             # frames pushed (or error-skipped)
        self._wk_error = None
        self._wk_stop = False
        # in-flight bound: backpressure so a slow downstream or a burst
        # does not queue unbounded frames inside the element
        self._wk_sem = threading.Semaphore(self._workers_n * 2)
        self._wk_threads = [
            threading.Thread(target=self._worker_loop, args=(fw,),
                             daemon=True, name=f"invoke:{self.name}:{i}")
            for i, fw in enumerate(backends)]
        self._wk_pusher = threading.Thread(
            target=self._pusher_loop, daemon=True,
            name=f"invoke-push:{self.name}")
        for t in self._wk_threads:
            t.start()
        self._wk_pusher.start()

    def _submit_frame(self, tensors, buf: TensorBuffer) -> FlowReturn:
        self._wk_sem.acquire()
        with self._wk_cv:
            if self._wk_stop:
                self._wk_sem.release()
                return FlowReturn.EOS
            if self._wk_error is not None:
                self._wk_sem.release()
                return FlowReturn.ERROR
            seq = self._wk_seq
            self._wk_seq += 1
            # enqueue under the cv: _stop_workers sets _wk_stop under the
            # same lock BEFORE queueing the pool's exit sentinels, so a
            # task can never land behind a sentinel (it would be dropped
            # by the exiting workers while counted in _wk_seq, wedging
            # the pushed>=seq drain condition)
            self._wk_tasks.put((seq, list(tensors), buf))
        return FlowReturn.OK

    def _worker_loop(self, fw) -> None:
        import time as _time

        while True:
            item = self._wk_tasks.get()
            if item is None:
                return
            seq, tensors, buf = item
            pl = self.pipeline
            tracer = pl.tracer if pl is not None else None
            try:
                if tracer is not None:
                    # per-invoke span on the worker thread: proctime
                    # lands under "<name>:invoke" (chain() only covers
                    # the submit), and the backend's device-invoke
                    # annotation records inside this frame
                    tracer.enter(self.name + ":invoke", buf)
                try:
                    if self._emit_device:
                        outs = fw.invoke(tensors, emit_device=True)
                    else:
                        outs = fw.invoke(tensors)
                finally:
                    if tracer is not None:
                        tracer.exit()
                res = (buf, list(outs), None,
                       _time.monotonic_ns() if tracer is not None else 0)
            except Exception as exc:  # noqa: BLE001 — surfaced by pusher
                res = (buf, None, exc, 0)
            with self._wk_cv:
                self._wk_results[seq] = res
                self._wk_cv.notify_all()

    def _pusher_loop(self) -> None:
        """Reassemble worker results in strict sequence order and push
        downstream — output order is exactly arrival order regardless of
        per-frame invoke latency jitter."""
        while True:
            with self._wk_cv:
                self._wk_cv.wait_for(
                    lambda: self._wk_pushed in self._wk_results
                    or (self._wk_stop
                        and self._wk_pushed >= self._wk_seq))
                if self._wk_pushed not in self._wk_results:
                    return              # stopped and fully drained
                buf, outs, exc, ready_ns = self._wk_results.pop(
                    self._wk_pushed)
                failed = self._wk_error is not None
            if ready_ns:
                # reorder-wait: the result was finished at ready_ns but
                # held for strict stream order (obs/attrib.py state)
                pl = self.pipeline
                tracer = pl.tracer if pl is not None else None
                if tracer is not None and tracer.ring is not None:
                    import time as _time

                    ctx = buf.extra.get("nns_trace")
                    tracer.annotate_span(
                        "reorder-wait", ready_ns, _time.monotonic_ns(),
                        seq=buf.extra.get("nns_seq", -1),
                        trace_id=ctx.trace_id if ctx else 0)
            if not failed:
                try:
                    if exc is not None:
                        raise exc
                    if self._push_result(buf, outs) is FlowReturn.ERROR:
                        raise RuntimeError(
                            f"{self.name}: downstream error from invoke "
                            "worker")
                except Exception as err:  # noqa: BLE001
                    with self._wk_cv:
                        self._wk_error = err
                    if self.pipeline is not None:
                        self.pipeline.post_error(self, err)
            # count the frame pushed (or skipped after an error, so
            # draining still converges) and free a submit slot
            with self._wk_cv:
                self._wk_pushed += 1
                self._wk_cv.notify_all()
            self._wk_sem.release()

    def _drain_workers(self) -> None:
        """Block until every submitted frame has been pushed, in order
        (EOS, renegotiation, model swap).  Raises on a worker/downstream
        failure so the event path posts a pipeline error."""
        if getattr(self, "_workers_n", 1) <= 1:
            return
        with self._wk_cv:
            self._wk_cv.wait_for(
                lambda: self._wk_pushed >= self._wk_seq)
            if self._wk_error is not None:
                raise RuntimeError(
                    f"{self.name}: invoke worker failed while draining"
                ) from self._wk_error

    def unblock(self):
        if getattr(self, "_workers_n", 1) > 1:
            with self._wk_cv:
                self._wk_stop = True
                self._wk_cv.notify_all()
            self._wk_sem.release()   # wake a producer blocked on the bound

    def _stop_workers(self) -> None:
        if getattr(self, "_workers_n", 1) <= 1:
            return
        with self._wk_cv:
            self._wk_stop = True
            self._wk_cv.notify_all()
        for _ in self._wk_threads:
            self._wk_tasks.put(None)
        for t in self._wk_threads:
            t.join(timeout=10)
        self._wk_pusher.join(timeout=10)
        for fw in self._wk_backends:
            if fw is not self.fw:
                fw.close()
        self._workers_n = 1

    # -- micro-batching ------------------------------------------------------
    def _collect_frame(self, tensors, buf: TensorBuffer) -> FlowReturn:
        """Append one frame to the collecting bucket; dispatch when it
        fills.  Caller holds the coalesce lock when the deadline watcher
        is active."""
        pl = self.pipeline
        if pl is not None and pl.tracer is not None \
                and pl.tracer.ring is not None:
            # wait-state attribution (obs/attrib.py): bucket-coalescing
            # arrival stamp; _push_inflight turns it into per-frame
            # queue-wait + device-invoke spans.  One tracer test per
            # frame on the (interpreted-only) batch path.
            import time

            buf.extra["nns_coll_ns"] = time.monotonic_ns()
        if self._bucket.add((list(tensors), buf)):
            return self._dispatch_pending()
        return FlowReturn.OK

    def _dispatch_pending(self) -> FlowReturn:
        """Dispatch the collecting batch, then — once the in-flight queue
        is at depth — push the OLDEST batch's results (d2h copies of
        every queued batch overlap this batch's collection; deeper
        queues overlap more dispatch round-trips)."""
        t0 = self._bucket.opened_at()
        items = self._bucket.take()
        pending = [tensors for tensors, _ in items]
        bufs = [b for _, b in items]
        if bufs and "nns_coll_ns" in bufs[0].extra:
            import time

            d0 = time.monotonic_ns()
            for b in bufs:
                b.extra["nns_disp_ns"] = d0
        if self._emit_device:
            handle = self.fw.invoke_batched(pending, self._batch,
                                            emit_device=True)
        else:
            handle = self.fw.invoke_batched(pending, self._batch)
        self._inflight.append((bufs, handle, t0))
        if len(self._inflight) > self._inflight_depth:
            return self._push_inflight(self._inflight.popleft())
        return FlowReturn.OK

    def _push_inflight(self, inflight) -> FlowReturn:
        bufs, handle, _t0 = inflight
        per_frame = handle.views() if self._emit_device else handle.wait()
        pl = self.pipeline
        tracer = pl.tracer if pl is not None else None
        if tracer is not None and tracer.ring is not None:
            # per-frame attribution of the shared batch (obs/attrib.py):
            # arrival → dispatch is queue-wait (bucket fill + in-flight
            # backlog), dispatch → host materialization is this frame's
            # device window (every batch peer overlaps the same one —
            # per-frame wall-clock truth, not a 1/n share)
            import time

            t1 = time.monotonic_ns()
            for buf in bufs:
                coll = buf.extra.pop("nns_coll_ns", None)
                disp = buf.extra.pop("nns_disp_ns", None)
                if coll is None or disp is None:
                    continue
                ctx = buf.extra.get("nns_trace")
                tid = ctx.trace_id if ctx else 0
                seq = buf.extra.get("nns_seq", -1)
                tracer.annotate_span("queue-wait", coll, disp,
                                     seq=seq, trace_id=tid)
                tracer.annotate_span("device-invoke", disp, t1,
                                     seq=seq, trace_id=tid)
        ret = FlowReturn.OK
        for buf, outs in zip(bufs, per_frame):
            r = self._push_result(buf, list(outs))
            if r is FlowReturn.ERROR:
                return r
            ret = r
        return ret

    def _deadline_loop(self) -> None:
        """Coalescer watcher: dispatch a partial bucket (and flush
        expired in-flight batches) once the oldest queued frame has
        waited batch-timeout-ms.  Under throughput load buckets fill
        before their deadline and this thread just sleeps; on underrun
        it bounds per-frame latency."""
        import time

        to = self._batch_deadline
        while not self._deadline_stop.is_set():
            try:
                with self._coalesce_lock:
                    now = time.monotonic()
                    oldest = self._oldest_t0()
                    if oldest is not None and now - oldest >= to:
                        self._flush_expired(now)
                        oldest = self._oldest_t0()
                wait = (to / 2 if oldest is None
                        else oldest + to - time.monotonic())
            except Exception as exc:  # noqa: BLE001 — becomes pipeline err
                if self.pipeline is not None:
                    self.pipeline.post_error(self, exc)
                return
            self._deadline_stop.wait(max(0.001, min(wait, to / 2)))

    def _oldest_t0(self):
        """Arrival time of the oldest un-pushed frame (None when idle).
        Caller holds the coalesce lock."""
        if self._inflight:
            return self._inflight[0][2]
        return self._bucket.opened_at()

    def _flush_expired(self, now: float) -> None:
        """Push every batch whose oldest frame's budget expired, oldest
        first; dispatch the partial bucket if ITS budget expired.  Caller
        holds the coalesce lock; stream order is preserved because both
        this thread and chain() push under it."""
        to = self._batch_deadline
        while self._inflight and now - self._inflight[0][2] >= to:
            if self._push_inflight(self._inflight.popleft()) \
                    is FlowReturn.ERROR:
                raise RuntimeError(
                    f"{self.name}: downstream error on deadline flush")
        if self._bucket.expired(now):
            # _dispatch_pending may itself push an over-depth batch:
            # its ERROR must propagate like the loop pushes' do
            if self._dispatch_pending() is FlowReturn.ERROR:
                raise RuntimeError(
                    f"{self.name}: downstream error on deadline flush")
            while self._inflight and now - self._inflight[0][2] >= to:
                if self._push_inflight(self._inflight.popleft()) \
                        is FlowReturn.ERROR:
                    raise RuntimeError(
                        f"{self.name}: downstream error on deadline flush")

    def _drain_batches(self) -> None:
        """Flush the collecting partial batch and the in-flight batch, in
        stream order (EOS, renegotiation, model swap).  A downstream ERROR
        raises so the event path posts a pipeline error, matching the
        per-frame path's propagation."""
        if self._batch <= 1:
            return
        if self._batch_deadline > 0:
            with self._coalesce_lock:
                self._drain_batches_locked()
        else:
            self._drain_batches_locked()

    def _drain_batches_locked(self) -> None:
        ret = FlowReturn.OK
        if self._bucket.fill:
            ret = self._dispatch_pending()
        while self._inflight:
            r = self._push_inflight(self._inflight.popleft())
            ret = r if r is FlowReturn.ERROR else ret
        if ret is FlowReturn.ERROR:
            raise RuntimeError(
                f"{self.name}: downstream error while draining batches")

    # -- events --------------------------------------------------------------
    def on_upstream_event(self, pad, event):
        if isinstance(event, QoSEvent):
            # Reference src_event QOS handling (:1454-1485): derive a
            # throttling interval from the reported slowdown and the
            # stream's frame cadence; a catch-up report (jitter <= 0)
            # clears it.  Also auto-enables latency accounting.
            if event.jitter_ns <= 0:
                self._throttle_ns = 0
            else:
                rate = getattr(self, "_in_config", None)
                rate = rate.rate if rate is not None else None
                if rate and rate > 0:
                    frame_ns = (1_000_000_000 * rate.denominator
                                // rate.numerator)
                elif event.proportion > 1.0:
                    # jitter = dur·(proportion-1) at the reporter, so the
                    # frame duration is recoverable even without caps rate
                    frame_ns = max(
                        int(event.jitter_ns / (event.proportion - 1.0)), 1)
                else:
                    frame_ns = max(event.jitter_ns, 1)
                self._throttle_ns = int(frame_ns * max(1.0,
                                                       event.proportion))
                self.latency_report = True
            # a fuse=xla segment cannot express the per-buffer drop
            # state: drop its plan so the next buffer recompiles at the
            # fuse-python tier (and back, once a catch-up report clears
            # the throttle) — lower_reason() answers per current state
            pl = self.pipeline
            if pl is not None and getattr(pl, "planner", None) is not None \
                    and pl.planner.tier == "xla":
                pl.planner.invalidate(element=self)
            # keep propagating so upstream adapters (tensor_rate, sources)
            # can throttle too — the filter is a participant, not the owner
            super().on_upstream_event(pad, event)
            return True
        if isinstance(event, CustomEvent) and \
                event.name == "nns/device-reduce":
            # Reduction pushdown from a downstream decoder: fuse its pure
            # device reduction into the backend executable and re-announce
            # the (smaller) output caps.  The new caps travel in-band, so
            # buffers already in flight keep the old shape and decoders
            # dispatch on actual tensor shapes.
            fn = event.data["fn"]
            out_info = event.data["out_info"]
            # NOTE: no draining and no compiling here.  This handler can
            # run on a downstream queue's drain thread, where pushing
            # data or blocking for seconds deadlocks the pipeline (the
            # invariant is "never push DATA downstream from the drain
            # thread"; caps/event markers are exempt — queues enqueue
            # them unbounded).  In-flight batches keep the OLD output
            # shape and decoders dispatch on actual tensor shapes, so
            # ordering stays correct without a drain.
            if self._out_comb is not None:
                # output-combination re-indexes/mixes the model outputs
                # AFTER invoke; a reduction computed against the combined
                # view cannot be fused onto the raw outputs
                return False
            if getattr(self, "_workers_n", 1) > 1:
                # the worker pool invokes concurrently, possibly on
                # per-worker backend instances: fusing the reduction into
                # self.fw alone would emit mixed output shapes under the
                # reduced caps (and mutate a shared backend mid-invoke).
                # Refusing keeps correctness — the decoder host-decodes.
                return False
            if not self.fw.set_postprocess(fn):
                return False
            # remember the fusion: a model reload rebuilds the backend
            # (close+open), which would silently drop the device-fused
            # tail back to host decode — the update handler re-applies it
            self._pushdown = fn
            if self._batch > 1:
                # the fusion rebuilt both executables; re-warm on the
                # next chain() call (producer thread)
                self._rewarm = True
            self._out_config = TensorsConfig(info=out_info,
                                             rate=self._in_config.rate)
            from ..tensor.caps_util import caps_from_config

            self.announce_src_caps(caps_from_config(self._out_config))
            return True
        return super().on_upstream_event(pad, event)

    def on_event(self, pad, event):
        from ..pipeline.element import EOSEvent

        if isinstance(event, EOSEvent):
            self._drain_batches()
            self._drain_workers()   # all in-flight frames precede EOS
        if isinstance(event, CustomEvent) and \
                event.name == "tensor_filter_update_model":
            if not self.is_updatable:
                raise RuntimeError(f"{self.name}: not is-updatable")
            self._drain_batches()  # frames of the old model flush first
            self._drain_workers()
            try:
                self.fw.handle_event("reload_model", event.data)
                # per-worker backend instances serve the same model: a
                # reload that only swapped self.fw would leave workers
                # 1..N-1 silently answering with the OLD weights
                for wfw in getattr(self, "_wk_backends", []):
                    if wfw is not self.fw:
                        wfw.handle_event("reload_model", event.data)
            except Exception as exc:  # noqa: BLE001
                # a rejected reload keeps the old model serving — log and
                # keep streaming instead of erroring the pipeline (unless
                # the backend could not be restored at all)
                from ..utils.log import ml_logw

                if not self.fw.opened:
                    raise
                ml_logw("%s: model reload rejected, keeping old model: %s",
                        self.name, exc)
            self._reapply_pushdown()
            return  # consumed, like the reference custom-event sink
        super().on_event(pad, event)

    def _reapply_pushdown(self) -> None:
        """Restore a device-fused decoder reduction after a model reload:
        any close+open swap (new model name, or a rejected reload's
        rollback) rebuilt the backend WITHOUT the fused tail, so every
        output would silently pay the full d2h fetch + host decode
        again.  The reload interface check guarantees the model's
        tensor io is unchanged, so the stored reduction still applies.
        If the fresh backend refuses the fusion, fall back loudly to
        the full output caps (decoders dispatch on actual shapes, so
        correctness holds either way)."""
        if self._pushdown is None or not getattr(self.fw, "opened", False):
            return
        if self.fw.has_postprocess():
            # params-only fast path: the backend never closed, the fused
            # executable survived — re-fusing would compose the reduction
            # over the already-reduced outputs
            return
        if self.fw.set_postprocess(self._pushdown):
            if self._batch > 1:
                self._rewarm = True
            return
        from ..utils.log import ml_logw

        ml_logw("%s: device-reduce fusion could not be re-applied after "
                "reload; serving full outputs (host decode)", self.name)
        self._pushdown = None
        _, model_out = self.fw.get_model_info()
        self._out_config = TensorsConfig(info=model_out,
                                         rate=self._in_config.rate)
        from ..tensor.caps_util import caps_from_config

        self.announce_src_caps(caps_from_config(self._out_config))

    def report_latency(self) -> int:
        """LATENCY-query contribution: rolling average invoke latency in ns
        when latency-report is on (reference tensor_filter.c:1313-1377)."""
        if not self.latency_report:
            return 0
        lat_us = self.latency
        return lat_us * 1000 if lat_us > 0 else 0

    # -- stats readout (reference readable props :2163-2171) -----------------
    @property
    def latency(self) -> int:
        stats = getattr(self, "stats", None)
        return stats.latency_us if stats else -1

    @property
    def throughput(self) -> float:
        stats = getattr(self, "stats", None)
        return stats.throughput if stats else 0.0
