"""Misc elements: tensor_debug, join, tensor_crop, datareposrc.

- tensor_debug: in-band caps/meta probe (gsttensor_debug.c role).
- join: N→1 first-come forwarding without sync (gst/join/gstjoin.c).
- tensor_crop: crop a raw tensor stream using crop-info from a second
  flexible stream (gsttensor_crop.c: in-band dynamic shapes; output is
  flexible).
- datareposrc: file-based training-data source
  (gst/datarepo/gstdatareposrc.c: replayable datasets, e.g. MNIST .dat).
"""

from __future__ import annotations

import os
from fractions import Fraction
from typing import List, Optional

import numpy as np

from ..pipeline.caps import Caps
from ..pipeline.element import (CapsEvent, Element, EOSEvent, FlowReturn,
                                LoweredStep, Pad)
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import SECOND, TensorBuffer
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                flexible_tensors_caps, static_tensors_caps,
                                tensors_template_caps)
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensor.meta import TensorMetaInfo
from ..tensor.types import TensorType, dim_parse


@register_element
class Identity(Element):
    """Pass-through element (GStreamer ``identity`` role): forwards every
    buffer untouched.  Fusable — the unit of per-element dispatch-overhead
    measurement in ``tools/hotpath_bench.py --stage dispatch``.
    ``sleep-us`` emulates a fixed per-buffer cost (test/bench hook, the
    gst identity ``sleep-time`` analogue)."""

    FACTORY = "identity"
    PROPERTIES = {"sleep-us": (0, "sleep per buffer, microseconds")}

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")
        self.add_src_pad(Caps.any(), "src")

    def _forward(self, buf):
        if self.sleep_us:
            import time as _time

            _time.sleep(int(self.sleep_us) / 1e6)
        return buf

    def chain(self, pad, buf):
        return self.push(self._forward(buf))

    def plan_step(self):
        return self._forward

    def lower_reason(self):
        if int(self.sleep_us or 0):
            return "identity sleep-us emulates host work (untraceable)"
        return None

    def lower_step(self):
        if self.lower_reason() is not None:
            return None
        return LoweredStep(lambda params, ts: ts)


@register_element
class TensorDebug(Element):
    """Logs caps/buffer meta in-band (console-output parity with
    gsttensor_debug.c)."""

    FACTORY = "tensor_debug"
    PROPERTIES = {"output": ("console", "console|silent"),
                  "capture": (False, "keep a record in .log")}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.log: List[str] = []

    def _make_pads(self):
        self.add_sink_pad(Caps.any(), "sink")
        self.add_src_pad(Caps.any(), "src")

    def set_caps(self, pad, caps):
        self._note(f"caps: {caps}")
        self.src_pad.push_event(CapsEvent(caps))

    def _observe(self, buf):
        shapes = [tuple(getattr(t, "shape", ())) for t in buf.tensors]
        self._note(f"buffer pts={buf.pts} n={buf.num_tensors} shapes={shapes}")
        return buf

    def chain(self, pad, buf):
        return self.push(self._observe(buf))

    def plan_step(self):
        return self._observe

    def lower_reason(self):
        if str(self.output) == "console" or bool(self.capture):
            return ("tensor_debug output=console/capture has per-buffer "
                    "side effects (set output=silent to lower)")
        return None

    def lower_step(self):
        if self.lower_reason() is not None:
            return None
        return LoweredStep(lambda params, ts: ts)

    def _note(self, msg: str) -> None:
        if bool(self.capture):
            self.log.append(msg)
        if str(self.output) == "console":
            print(f"[{self.name}] {msg}")


@register_element
class Join(Element):
    """First-come N→1 forwarding, no sync (gst/join/gstjoin.c)."""

    FACTORY = "join"

    def _make_pads(self):
        self.add_src_pad(Caps.any(), "src")

    def request_sink_pad(self) -> Pad:
        return self.add_sink_pad(Caps.any())

    def start(self):
        self._caps_sent = False
        self._eos_count = 0

    def set_caps(self, pad, caps):
        if not self._caps_sent:
            self._caps_sent = True
            self.src_pad.push_event(CapsEvent(caps))

    def chain(self, pad, buf):
        return self.push(buf)

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self._eos_count += 1
            if self._eos_count >= len(self.sink_pads):
                self.src_pad.push_event(EOSEvent())
            return
        super().on_event(pad, event)


@register_element
class TensorCrop(Element):
    """Crop raw tensors with crop-info from a second (flexible) stream.

    sink_0 = raw stream, sink_1 = crop info: each crop-info buffer holds a
    tensor of int32 [[x, y, w, h], ...] regions (reference flex-tensor crop
    info, gsttensor_crop.c:494-649).  Output: flexible stream, one cropped
    tensor per region.
    """

    FACTORY = "tensor_crop"
    PROPERTIES = {
        "lateness": (-1, "reference crop-info sync tolerance in ms "
                         "(accepted for launch-line parity; this crop "
                         "pairs raw/info buffers exactly by arrival "
                         "order)"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "raw")
        self.add_sink_pad(tensors_template_caps(), "info")
        self.add_src_pad(flexible_tensors_caps(), "src")

    def start(self):
        self._raw: List[TensorBuffer] = []
        self._info: List[TensorBuffer] = []
        self._announced = False
        self._eos = 0

    def set_caps(self, pad, caps):
        if not self._announced:
            self._announced = True
            rate = config_from_caps(caps).rate or Fraction(0, 1)
            from ..tensor.types import TensorFormat

            self.announce_src_caps(caps_from_config(
                TensorsConfig(format=TensorFormat.FLEXIBLE, rate=rate)))

    def chain(self, pad, buf):
        (self._raw if pad.name == "raw" else self._info).append(buf)
        while self._raw and self._info:
            raw = self._raw.pop(0)
            info = self._info.pop(0)
            out = self._crop(raw, info)
            ret = self.push(out)
            if ret is FlowReturn.ERROR:
                return ret
        return FlowReturn.OK

    def _crop(self, raw: TensorBuffer, info: TensorBuffer) -> TensorBuffer:
        frame = raw.np(0)  # (H, W, C) video-like or (W,) 1-D
        regions = np.asarray(info.np(0)).reshape(-1, 4)
        tensors, metas = [], []
        for x, y, w, h in regions.astype(int):
            if frame.ndim >= 2:
                crop = frame[y:y + h, x:x + w]
            else:
                crop = frame[x:x + w]
            crop = np.ascontiguousarray(crop)
            tensors.append(crop)
            metas.append(TensorMetaInfo.from_info(TensorInfo.from_np(crop)))
        out = raw.with_tensors(tensors)
        out.metas = metas
        return out

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            self._eos += 1
            if self._eos >= 2:
                self.src_pad.push_event(EOSEvent())
            return
        if pad.name == "raw":
            super().on_event(pad, event)


@register_element
class DataRepoSrc(Source):
    """Replayable file dataset source (gstdatareposrc.c role): reads fixed-
    size frames from a binary file, announcing caps from input-dim/type."""

    FACTORY = "datareposrc"
    PROPERTIES = {
        "location": (None, "data file path"),
        "input-dim": (None, "frame dims, e.g. 1:1:784:1"),
        "input-type": (None, "frame dtype"),
        "epochs": (1, "number of passes over the file"),
        "framerate": ("0/1", "announced rate"),
    }

    def _make_pads(self):
        self.add_src_pad(static_tensors_caps(), "src")

    def start(self):
        if not self.location or not os.path.exists(str(self.location)):
            raise ValueError(f"{self.name}: bad location {self.location!r}")
        dims = [dim_parse(d) for d in str(self.input_dim).split(",")]
        types = [TensorType.from_string(t)
                 for t in str(self.input_type).split(",")]
        self._infos = TensorsInfo(
            [TensorInfo(t, d) for t, d in zip(types, dims)])
        self._frame_bytes = self._infos.total_size()
        # native prefetching reader (tensorwire reader.cc): file IO
        # overlaps pipeline compute with bounded memory; Python mmap
        # fallback without the .so
        from ..native import RepoReader

        try:
            self._reader = RepoReader(str(self.location),
                                      self._frame_bytes, capacity=8,
                                      wrap=True)
        except ValueError as e:
            raise ValueError(f"{self.name}: {e}") from e
        self._num_frames = self._reader.num_frames

    def stop(self):
        if getattr(self, "_reader", None) is not None:
            self._reader.close()
            self._reader = None
        super().stop()

    def negotiate(self) -> Caps:
        cfg = TensorsConfig(info=self._infos,
                            rate=Fraction(str(self.framerate)))
        return caps_from_config(cfg)

    def create(self) -> Optional[TensorBuffer]:
        reader = self._reader     # local ref: stop() may null the attr
        if reader is None:
            return None
        total = int(self.epochs) * self._num_frames
        got = reader.next_frame()
        if got is None or got[0] >= total:
            return None
        index, chunk = got
        tensors = []
        pos = 0
        for info in self._infos:
            raw = np.frombuffer(chunk, np.uint8, count=info.size, offset=pos)
            tensors.append(raw.view(info.np_dtype).reshape(info.np_shape))
            pos += info.size
        return TensorBuffer(tensors=tensors, pts=index * SECOND // 30)
