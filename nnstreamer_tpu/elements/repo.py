"""tensor_reposink / tensor_reposrc: in-process circular stream repository.

Parity with gst/nnstreamer/elements/gsttensor_repo.c (+reposink/reposrc):
a process-global slot table keyed by ``slot-index`` lets a pipeline feed
its own upstream (recurrent topologies) without a direct element link.
"""

from __future__ import annotations

import queue as _queue
import time
from typing import Dict, Optional

from ..analysis.sanitizer import make_condition
from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import tensors_template_caps


class _Repo:
    """Process-global slot table (reference gsttensor_repo.c table).

    Caps registration is condition-driven: a reposrc waiting for the
    writer's caps blocks on the table condition and wakes the moment
    ``set_caps`` lands (the 0.02 s poll this replaces burned 50 wakeups
    per second of startup skew for a median wait of one)."""

    def __init__(self) -> None:
        self._slots: Dict[int, _queue.Queue] = {}
        self._caps: Dict[int, Caps] = {}
        self._cv = make_condition("repo")

    def slot(self, index: int) -> _queue.Queue:
        with self._cv:
            if index not in self._slots:
                self._slots[index] = _queue.Queue(maxsize=32)
            return self._slots[index]

    def set_caps(self, index: int, caps: Caps) -> None:
        with self._cv:
            self._caps[index] = caps
            self._cv.notify_all()

    def get_caps(self, index: int) -> Optional[Caps]:
        with self._cv:
            return self._caps.get(index)

    def wait_caps(self, index: int, timeout: float,
                  cancelled=lambda: False) -> Optional[Caps]:
        """Block until slot ``index`` has caps (the writer's set_caps
        wakes us), the deadline passes, or ``cancelled()`` turns true
        (re-checked on each wakeup; :func:`wake` forces one)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                caps = self._caps.get(index)
                if caps is not None or cancelled():
                    return caps
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def wake(self) -> None:
        """Interrupt waiters so they re-check their cancel condition
        (element teardown)."""
        with self._cv:
            self._cv.notify_all()

    def clear(self) -> None:
        with self._cv:
            self._slots.clear()
            self._caps.clear()
            self._cv.notify_all()


repo = _Repo()


@register_element
class TensorRepoSink(Element):
    FACTORY = "tensor_reposink"
    PROPERTIES = {
        "slot-index": (0, "repository slot"),
        "signal-rate": (0, "reference reposink property (emission rate "
                           "limiter there; accepted for launch-line "
                           "parity — this sink emits no signals)"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def set_caps(self, pad, caps):
        repo.set_caps(int(self.slot_index), caps)

    def chain(self, pad, buf):
        repo.slot(int(self.slot_index)).put(buf)
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            repo.slot(int(self.slot_index)).put(None)
            self.post_eos_reached()


@register_element
class TensorRepoSrc(Source):
    FACTORY = "tensor_reposrc"
    PROPERTIES = {"slot-index": (0, "repository slot"),
                  "caps": (None, "caps to announce (else slot caps)")}

    def start(self):
        # first create() emits a zero dummy buffer (reference
        # gsttensor_reposrc.c:287-337): a recurrent cycle's state source
        # must produce frame 0 before the loop has written anything
        self._ini = False

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    #: in-band wake marker for the blocking slot-queue get in create()
    #: (same treatment as AppSrc._WAKE: teardown enqueues it so the
    #: reader never needs a timeout poll)
    _WAKE = object()

    def negotiate(self) -> Caps:
        if self.caps is not None:
            c = self.caps
            caps = Caps.from_string(c) if isinstance(c, str) else c
            self._neg_caps = caps
            return caps
        # wait (event-driven) for the writer to register caps; _halt()
        # wakes the condition so teardown never rides out the deadline
        c = repo.wait_caps(int(self.slot_index), timeout=2.0,
                           cancelled=self._halted.is_set)
        if c is not None:
            self._neg_caps = c
            return c
        raise RuntimeError(f"{self.name}: no caps in slot {self.slot_index}")

    def _dummy_buffer(self) -> Optional[TensorBuffer]:
        from ..tensor.caps_util import config_from_caps

        try:
            import numpy as np

            cfg = config_from_caps(self._neg_caps)
            zeros = [np.zeros(i.np_shape, i.np_dtype) for i in cfg.info]
            return TensorBuffer(tensors=zeros, pts=0)
        except Exception:
            return None  # flexible/unparseable caps: wait for real data

    def _halt(self) -> None:
        # flag first, then wake both wait sites: the caps condition (a
        # negotiate still waiting re-checks cancelled) and the slot
        # queue (create's blocking get consumes the marker and exits)
        self._halted.set()
        repo.wake()
        try:
            repo.slot(int(self.slot_index)).put_nowait(self._WAKE)
        except _queue.Full:
            pass   # reader isn't blocked on an empty queue: no wake needed
        super()._halt()

    def create(self) -> Optional[TensorBuffer]:
        q = repo.slot(int(self.slot_index))
        if not getattr(self, "_ini", True):
            self._ini = True
            dummy = self._dummy_buffer()
            if dummy is not None:
                return dummy
        # blocking get with NO timeout: event-driven (the 0.1 s poll this
        # replaces woke 10x/s for the whole stream); _halt()'s in-band
        # _WAKE marker interrupts it at teardown
        while not self._halted.is_set():
            item = q.get()
            if item is self._WAKE:
                continue   # teardown (or stale) marker: re-check halted
            return item    # None = EOS sentinel from reposink
        return None
