"""tensor_reposink / tensor_reposrc: in-process circular stream repository.

Parity with gst/nnstreamer/elements/gsttensor_repo.c (+reposink/reposrc):
a process-global slot table keyed by ``slot-index`` lets a pipeline feed
its own upstream (recurrent topologies) without a direct element link.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Dict, Optional

from ..pipeline.caps import Caps
from ..pipeline.element import Element, EOSEvent, FlowReturn
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import tensors_template_caps


class _Repo:
    """Process-global slot table (reference gsttensor_repo.c table)."""

    def __init__(self) -> None:
        self._slots: Dict[int, _queue.Queue] = {}
        self._caps: Dict[int, Caps] = {}
        self._lock = threading.Lock()

    def slot(self, index: int) -> _queue.Queue:
        with self._lock:
            if index not in self._slots:
                self._slots[index] = _queue.Queue(maxsize=32)
            return self._slots[index]

    def set_caps(self, index: int, caps: Caps) -> None:
        with self._lock:
            self._caps[index] = caps

    def get_caps(self, index: int) -> Optional[Caps]:
        with self._lock:
            return self._caps.get(index)

    def clear(self) -> None:
        with self._lock:
            self._slots.clear()
            self._caps.clear()


repo = _Repo()


@register_element
class TensorRepoSink(Element):
    FACTORY = "tensor_reposink"
    PROPERTIES = {
        "slot-index": (0, "repository slot"),
        "signal-rate": (0, "reference reposink property (emission rate "
                           "limiter there; accepted for launch-line "
                           "parity — this sink emits no signals)"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")

    def set_caps(self, pad, caps):
        repo.set_caps(int(self.slot_index), caps)

    def chain(self, pad, buf):
        repo.slot(int(self.slot_index)).put(buf)
        return FlowReturn.OK

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            repo.slot(int(self.slot_index)).put(None)
            self.post_eos_reached()


@register_element
class TensorRepoSrc(Source):
    FACTORY = "tensor_reposrc"
    PROPERTIES = {"slot-index": (0, "repository slot"),
                  "caps": (None, "caps to announce (else slot caps)")}

    def start(self):
        # first create() emits a zero dummy buffer (reference
        # gsttensor_reposrc.c:287-337): a recurrent cycle's state source
        # must produce frame 0 before the loop has written anything
        self._ini = False

    def _make_pads(self):
        self.add_src_pad(tensors_template_caps(), "src")

    def negotiate(self) -> Caps:
        if self.caps is not None:
            c = self.caps
            caps = Caps.from_string(c) if isinstance(c, str) else c
            self._neg_caps = caps
            return caps
        # wait briefly for the writer to register caps
        import time

        for _ in range(100):
            c = repo.get_caps(int(self.slot_index))
            if c is not None:
                self._neg_caps = c
                return c
            time.sleep(0.02)
        raise RuntimeError(f"{self.name}: no caps in slot {self.slot_index}")

    def _dummy_buffer(self) -> Optional[TensorBuffer]:
        from ..tensor.caps_util import config_from_caps

        try:
            import numpy as np

            cfg = config_from_caps(self._neg_caps)
            zeros = [np.zeros(i.np_shape, i.np_dtype) for i in cfg.info]
            return TensorBuffer(tensors=zeros, pts=0)
        except Exception:
            return None  # flexible/unparseable caps: wait for real data

    def create(self) -> Optional[TensorBuffer]:
        q = repo.slot(int(self.slot_index))
        if not getattr(self, "_ini", True):
            self._ini = True
            dummy = self._dummy_buffer()
            if dummy is not None:
                return dummy
        while not self._halted.is_set():
            try:
                item = q.get(timeout=0.1)
            except _queue.Empty:
                continue
            return item  # None = EOS sentinel from reposink
        return None
