"""tensor_merge / tensor_split: dimension-wise concatenation and slicing.

Parity with gst/nnstreamer/elements/gsttensor_merge.c (N single-tensor
streams → one tensor concatenated along a dimension, PTS-synced) and
gsttensor_split.c (one tensor → N streams sliced by ``tensorseg``).
"""

from __future__ import annotations

from fractions import Fraction
from typing import Dict, List

import numpy as np

from ..pipeline.clock import CollectPads, SyncMode, parse_sync_option
from ..pipeline.element import CapsEvent, Element, EOSEvent, FlowReturn, Pad
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                static_tensors_caps)
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo


@register_element
class TensorMerge(Element):
    """mode=linear option=<dim> concatenates along the reference dim index
    (innermost-first), i.e. numpy axis ``ndim-1-dim``."""

    FACTORY = "tensor_merge"
    PROPERTIES = {
        "mode": ("linear", "only 'linear' (like the reference's main mode)"),
        "option": (0, "reference dim index to concat along"),
        "sync-mode": ("slowest", "nosync|slowest|basepad|refresh"),
        "sync-option": (None, "basepad: '<pad>:<duration_ns>'"),
    }

    def _make_pads(self):
        self.add_src_pad(static_tensors_caps(), "src")

    def request_sink_pad(self) -> Pad:
        return self.add_sink_pad(static_tensors_caps())

    def start(self):
        import threading

        if str(self.mode) != "linear":
            raise ValueError(f"{self.name}: unsupported mode {self.mode}")
        self._dim = int(self.option)
        dur, base_pad = parse_sync_option(self.sync_option)
        self._collect = CollectPads(len(self.sink_pads),
                                    SyncMode.from_string(self.sync_mode),
                                    dur, base_pad=base_pad)
        self._pad_index = {p.name: i for i, p in enumerate(self.sink_pads)}
        self._pad_configs: Dict[int, TensorsConfig] = {}
        self._announced = False
        self._sent_eos = False
        self._eos_lock = threading.Lock()

    def set_caps(self, pad, caps):
        idx = self._pad_index[pad.name]
        cfg = config_from_caps(caps)
        if cfg.info.num_tensors != 1:
            raise ValueError(f"{self.name}: merge needs single-tensor pads")
        self._pad_configs[idx] = cfg
        if len(self._pad_configs) == len(self.sink_pads) and not self._announced:
            base = self._pad_configs[0].info[0]
            total = 0
            for i in range(len(self.sink_pads)):
                info = self._pad_configs[i].info[0]
                dims = list(info.dims) + [1] * (len(base.dims) - len(info.dims))
                total += dims[self._dim] if self._dim < len(dims) else 1
            out_dims = list(base.dims)
            while len(out_dims) <= self._dim:
                out_dims.append(1)
            out_dims[self._dim] = total
            cfg_out = TensorsConfig(
                info=TensorsInfo([TensorInfo(base.dtype, tuple(out_dims))]),
                rate=self._pad_configs[0].rate or Fraction(0, 1))
            self._announced = True
            self.announce_src_caps(caps_from_config(cfg_out))

    def chain(self, pad, buf):
        idx = self._pad_index[pad.name]
        if self._sent_eos:
            return FlowReturn.EOS
        frame_set = self._collect.push(idx, buf)
        if frame_set is None:
            return FlowReturn.OK
        ret = self.push(self._combine(frame_set))
        if self._collect.exhausted():
            self._send_eos_once()
            return FlowReturn.EOS
        return ret

    def _send_eos_once(self) -> None:
        with self._eos_lock:
            if self._sent_eos:
                return
            self._sent_eos = True
        self.src_pad.push_event(EOSEvent())

    def _combine(self, frame_set: List[TensorBuffer]) -> TensorBuffer:
        arrays = [b.np(0) for b in frame_set]
        # the concat dim may address a padded NNS dim beyond the true
        # rank (reference 'option=2' on rank-1 tensors; set_caps pads
        # the announced dims the same way) — NNS trailing dims are
        # LEADING numpy axes, so pad with leading 1-axes to cover it
        nd = max(arrays[0].ndim, self._dim + 1)
        arrays = [a.reshape((1,) * (nd - a.ndim) + a.shape)
                  for a in arrays]
        axis = nd - 1 - self._dim
        merged = np.concatenate(arrays, axis=axis)
        pts = max((b.pts or 0) for b in frame_set)
        return TensorBuffer(tensors=[merged], pts=pts,
                            duration=frame_set[0].duration)

    def on_event(self, pad, event):
        if isinstance(event, EOSEvent):
            if self._collect.set_eos(self._pad_index[pad.name]):
                self._send_eos_once()
            else:
                leftover = self._collect.finalize()
                if leftover is not None:
                    for fs in leftover:
                        self.push(self._combine(fs))
                    self._send_eos_once()
            return
        if self._pad_index[pad.name] == 0:
            super().on_event(pad, event)


@register_element
class TensorSplit(Element):
    """tensorseg=a,b,c slices the innermost-first dim 0... reference uses
    ``tensorseg`` as dim-sized chunks along a dimension (gsttensor_split.c);
    here ``option`` gives the reference dim and ``tensorseg`` the chunk
    sizes."""

    FACTORY = "tensor_split"
    PROPERTIES = {
        "tensorseg": (None, "comma list of slice sizes"),
        "option": (0, "reference dim index to slice along"),
    }

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")

    def request_src_pad(self) -> Pad:
        return self.add_src_pad(static_tensors_caps())

    def start(self):
        if self.tensorseg in (None, ""):
            raise ValueError(f"{self.name}: tensorseg required")
        self._segs = [int(x) for x in str(self.tensorseg).split(",")]
        self._dim = int(self.option)

    def set_caps(self, pad, caps):
        cfg = config_from_caps(caps)
        info = cfg.info[0]
        if sum(self._segs) != info.dims[self._dim]:
            raise ValueError(
                f"{self.name}: tensorseg sums to {sum(self._segs)}, dim is "
                f"{info.dims[self._dim]}")
        if len(self.src_pads) != len(self._segs):
            raise ValueError(
                f"{self.name}: {len(self.src_pads)} pads vs "
                f"{len(self._segs)} segments")
        for sp, seg in zip(self.src_pads, self._segs):
            dims = list(info.dims)
            dims[self._dim] = seg
            out = TensorsConfig(
                info=TensorsInfo([TensorInfo(info.dtype, tuple(dims))]),
                rate=cfg.rate)
            sp.push_event(CapsEvent(caps_from_config(out)))

    def chain(self, pad, buf):
        arr = buf.np(0)
        axis = arr.ndim - 1 - self._dim
        off = 0
        for sp, seg in zip(self.src_pads, self._segs):
            sl = [slice(None)] * arr.ndim
            sl[axis] = slice(off, off + seg)
            ret = sp.push(buf.with_tensors([np.ascontiguousarray(arr[tuple(sl)])]))
            if ret is FlowReturn.ERROR:
                return ret
            off += seg
        return FlowReturn.OK
