"""tensor_transform: element-wise ops on tensor streams.

Parity with gst/nnstreamer/elements/gsttensor_transform.c (mode enums at
gsttensor_transform.h:57-146): ``typecast``, ``arithmetic`` (op chains with
optional per-channel operands), ``transpose``, ``dimchg``, ``stand``
(standardization / dc-average), ``clamp``; ``apply`` selects which tensors
in the frame are transformed.

TPU-first re-design of the reference's ORC SIMD acceleration
(gsttensor_transform.c:463-533): when the incoming payload is already a
device array (e.g. directly downstream of an XLA filter), ops execute as
jax/jnp expressions so they fuse on-device and never force a host sync;
host numpy is used otherwise.  ``acceleration=false`` forces numpy.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

import numpy as np

from ..pipeline.element import Element, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                static_tensors_caps)
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensor.types import TensorType


def _xp(arr):
    """numpy for host arrays, jnp for device arrays (keeps transforms fused
    on-device — the TPU replacement for ORC SIMD)."""
    if isinstance(arr, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


@register_element
class TensorTransform(Element):
    FACTORY = "tensor_transform"
    PROPERTIES = {
        "mode": (None, "typecast|arithmetic|transpose|dimchg|stand|clamp"),
        "option": (None, "mode option string"),
        "acceleration": (True, "allow on-device (jnp) execution"),
        "apply": (None, "comma list of tensor indices to transform"),
    }

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")
        self.add_src_pad(static_tensors_caps(), "src")

    def start(self):
        mode = str(self.mode or "")
        option = str(self.option or "")
        self._apply_idx: Optional[List[int]] = None
        if self.apply not in (None, ""):
            self._apply_idx = [int(x) for x in str(self.apply).split(",")]
        if mode == "typecast":
            self._out_type = TensorType.from_string(option)
        elif mode == "arithmetic":
            self._ops = _parse_arith(option)
        elif mode == "transpose":
            self._perm = tuple(int(x) for x in option.split(":"))
            # the reference's transpose option is a permutation of axis
            # indices (gsttensor_transform.c); an out-of-range or
            # repeated index used to surface as a raw IndexError deep
            # in negotiation
            if sorted(self._perm) != list(range(len(self._perm))):
                raise ValueError(
                    f"{self.name}: transpose option must be a "
                    f"permutation of 0..{len(self._perm) - 1}, got "
                    f"{option!r}")
        elif mode == "dimchg":
            a, _, b = option.partition(":")
            self._dimchg = (int(a), int(b))
            if min(self._dimchg) < 0:
                raise ValueError(f"{self.name}: dimchg indices must be "
                                 f">= 0, got {option!r}")
        elif mode == "stand":
            parts = option.split(":")
            self._stand_mode = parts[0] or "default"
            self._stand_per_channel = len(parts) > 1 and parts[1] == "per-channel"
        elif mode == "clamp":
            lo, _, hi = option.partition(":")
            self._clamp = (float(lo), float(hi))
        else:
            raise ValueError(f"{self.name}: unknown mode {mode!r}")
        self._mode = mode

    # -- negotiation ---------------------------------------------------------
    def set_caps(self, pad, caps):
        cfg = config_from_caps(caps)
        out_infos = []
        for i, info in enumerate(cfg.info):
            if self._applies(i):
                out_infos.append(self._transform_info(info))
            else:
                out_infos.append(info.copy())
        self._out_config = TensorsConfig(info=TensorsInfo(out_infos),
                                         rate=cfg.rate)
        self.announce_src_caps(caps_from_config(self._out_config))

    def _applies(self, idx: int) -> bool:
        return self._apply_idx is None or idx in self._apply_idx

    def _transform_info(self, info: TensorInfo) -> TensorInfo:
        mode = self._mode
        if mode == "typecast":
            return TensorInfo(self._out_type, info.dims, info.name)
        if mode == "arithmetic":
            dtype = info.dtype
            for op, _ in self._ops:
                if op == "typecast":
                    dtype = _[0]
            return TensorInfo(dtype, info.dims, info.name)
        if mode == "transpose":
            if len(self._perm) < len(info.dims):
                raise ValueError(
                    f"{self.name}: transpose permutation rank "
                    f"{len(self._perm)} is below tensor rank "
                    f"{len(info.dims)} (dims {info.dims}) — a shorter "
                    "permutation would silently drop trailing dims")
            # reference transpose options are 4-index against NNS dims
            # padded with trailing 1s; pad, permute, strip the padding
            # back off (our dims convention is true-rank)
            padded = info.dims + (1,) * (len(self._perm) - len(info.dims))
            out = [padded[p] for p in self._perm]
            while len(out) > len(info.dims) and out[-1] == 1:
                out.pop()
            return TensorInfo(info.dtype, tuple(out), info.name)
        if mode == "dimchg":
            a, b = self._dimchg
            # same reference convention as transpose: indices address
            # NNS dims padded with trailing 1s (a verbatim '0:3' is
            # valid against a true-rank-3 tensor); pad, move, strip
            rank = max(len(info.dims), a + 1, b + 1)
            dims = list(info.dims) + [1] * (rank - len(info.dims))
            d = dims.pop(a)
            dims.insert(b, d)
            while len(dims) > len(info.dims) and dims[-1] == 1:
                dims.pop()
            return TensorInfo(info.dtype, tuple(dims), info.name)
        if mode == "stand":
            return TensorInfo(TensorType.FLOAT32, info.dims, info.name)
        return info.copy()  # clamp keeps type/shape

    # -- dataflow ------------------------------------------------------------
    def chain(self, pad, buf: TensorBuffer) -> FlowReturn:
        outs = []
        for i in range(buf.num_tensors):
            t = buf.tensors[i]
            if not bool(self.acceleration) or isinstance(t, np.ndarray):
                t = buf.np(i)
            if self._applies(i):
                target = self._out_config.info[i].dtype
                outs.append(self._transform(t, target))
            else:
                outs.append(t)
        return self.push(buf.with_tensors(outs))

    def _transform(self, arr: Any, target=None) -> Any:
        xp = _xp(arr)
        mode = self._mode
        if mode == "typecast":
            return arr.astype(self._out_type.np_dtype)
        if mode == "arithmetic":
            out = arr
            for op, operand in self._ops:
                if op == "typecast":
                    out = out.astype(operand[0].np_dtype)
                elif op == "add":
                    out = out + self._operand(operand, xp)
                elif op == "mul":
                    out = out * self._operand(operand, xp)
                elif op == "div":
                    out = out / self._operand(operand, xp)
            # numpy promotion (e.g. uint8 + 0.5 → float64) must not leak
            # past the caps we announced: cast back to the negotiated dtype
            if target is not None and out.dtype != target.np_dtype:
                out = out.astype(target.np_dtype)
            return out
        if mode == "transpose":
            # reference dims are innermost-first; numpy axes are
            # reversed — and a 4-index reference option against a
            # lower-rank tensor pads with 1s (NNS trailing dims =
            # leading numpy axes), permutes, then strips the padding
            orig_ndim = arr.ndim
            nd = len(self._perm)
            if arr.ndim < nd:
                arr = arr.reshape((1,) * (nd - arr.ndim) + arr.shape)
            np_perm = tuple(nd - 1 - self._perm[nd - 1 - ax]
                            for ax in range(nd))
            out = xp.transpose(arr, np_perm)
            while out.ndim > orig_ndim and out.shape[0] == 1:
                out = out.reshape(out.shape[1:])
            return out
        if mode == "dimchg":
            a, b = self._dimchg
            orig_ndim = arr.ndim
            nd = max(arr.ndim, a + 1, b + 1)
            if arr.ndim < nd:
                arr = arr.reshape((1,) * (nd - arr.ndim) + arr.shape)
            out = xp.moveaxis(arr, nd - 1 - a, nd - 1 - b)
            while out.ndim > orig_ndim and out.shape[0] == 1:
                out = out.reshape(out.shape[1:])
            return out
        if mode == "stand":
            x = arr.astype(np.float32)
            axes = (tuple(range(x.ndim - 1)) if self._stand_per_channel
                    else None)
            mean = x.mean(axis=axes, keepdims=axes is not None)
            if self._stand_mode == "dc-average":
                return x - mean
            std = x.std(axis=axes, keepdims=axes is not None)
            return (x - mean) / (std + 1e-10)
        if mode == "clamp":
            lo, hi = self._clamp
            return xp.clip(arr, lo, hi)
        raise AssertionError(mode)

    @staticmethod
    def _operand(operand, xp):
        vals = operand
        if len(vals) == 1:
            return vals[0]
        # per-channel operand along the innermost reference dim = last np
        # axis; kept floating so fractional operands aren't truncated
        return xp.asarray(vals, dtype=np.float64 if xp is np else None)


def _parse_arith(option: str) -> List[Tuple[str, Any]]:
    """Parse ``typecast:float32,add:-127.5,div:127.5`` chains (reference
    arithmetic option grammar, incl. multi-value per-channel operands
    ``add:1,2,3`` — values bind to the innermost dim)."""
    ops: List[Tuple[str, Any]] = []
    # split on commas that are followed by an op name, so per-channel value
    # lists keep their commas
    parts = re.split(r",(?=(?:typecast|add|mul|div|sub):)", option)
    for part in parts:
        if not part.strip():
            continue
        op, _, val = part.partition(":")
        op = op.strip()
        if op == "typecast":
            ops.append((op, [TensorType.from_string(val)]))
        elif op in ("add", "mul", "div", "sub"):
            vals = [float(v) for v in val.split(",")]
            if op == "sub":
                op, vals = "add", [-v for v in vals]
            ops.append((op, vals))
        else:
            raise ValueError(f"unknown arithmetic op {op!r}")
    return ops
