"""tensor_transform: element-wise ops on tensor streams.

Parity with gst/nnstreamer/elements/gsttensor_transform.c (mode enums at
gsttensor_transform.h:57-146): ``typecast``, ``arithmetic`` (op chains with
optional per-channel operands), ``transpose``, ``dimchg``, ``stand``
(standardization / dc-average), ``clamp``; ``apply`` selects which tensors
in the frame are transformed.

TPU-first re-design of the reference's ORC SIMD acceleration
(gsttensor_transform.c:463-533): when the incoming payload is already a
device array (e.g. directly downstream of an XLA filter), ops execute as
jax/jnp expressions so they fuse on-device and never force a host sync;
host numpy is used otherwise.  ``acceleration=false`` forces numpy.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

import numpy as np

from ..pipeline.element import Element, FlowReturn, LoweredStep
from ..pipeline.registry import register_element
from ..utils.log import ml_logw
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import (caps_from_config, config_from_caps,
                                static_tensors_caps)
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensor.types import TensorType


def _xp(arr):
    """numpy for host arrays, jnp for device arrays (keeps transforms fused
    on-device — the TPU replacement for ORC SIMD)."""
    if isinstance(arr, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


@register_element
class TensorTransform(Element):
    FACTORY = "tensor_transform"
    PROPERTIES = {
        "mode": (None, "typecast|arithmetic|transpose|dimchg|stand|clamp"),
        "option": (None, "mode option string"),
        "acceleration": (True, "allow on-device (jnp) execution"),
        "apply": (None, "comma list of tensor indices to transform"),
    }

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")
        self.add_src_pad(static_tensors_caps(), "src")

    def start(self):
        mode = str(self.mode or "")
        option = str(self.option or "")
        self._apply_idx: Optional[List[int]] = None
        if self.apply not in (None, ""):
            self._apply_idx = [int(x) for x in str(self.apply).split(",")]
        if mode == "typecast":
            self._out_type = TensorType.from_string(option)
        elif mode == "arithmetic":
            self._ops, self._ch_dim = _parse_arith(option)
        elif mode == "transpose":
            self._perm = tuple(int(x) for x in option.split(":"))
            # the reference's transpose option is a permutation of axis
            # indices (gsttensor_transform.c); an out-of-range or
            # repeated index used to surface as a raw IndexError deep
            # in negotiation
            if sorted(self._perm) != list(range(len(self._perm))):
                raise ValueError(
                    f"{self.name}: transpose option must be a "
                    f"permutation of 0..{len(self._perm) - 1}, got "
                    f"{option!r}")
        elif mode == "dimchg":
            a, _, b = option.partition(":")
            self._dimchg = (int(a), int(b))
            if min(self._dimchg) < 0:
                raise ValueError(f"{self.name}: dimchg indices must be "
                                 f">= 0, got {option!r}")
        elif mode == "stand":
            parts = option.split(":")
            self._stand_mode = parts[0] or "default"
            self._stand_per_channel = len(parts) > 1 and parts[1] == "per-channel"
        elif mode == "clamp":
            lo, _, hi = option.partition(":")
            self._clamp = (float(lo), float(hi))
        else:
            raise ValueError(f"{self.name}: unknown mode {mode!r}")
        self._mode = mode

    # -- negotiation ---------------------------------------------------------
    def set_caps(self, pad, caps):
        cfg = config_from_caps(caps)
        out_infos = []
        for i, info in enumerate(cfg.info):
            if self._applies(i):
                out_infos.append(self._transform_info(info))
            else:
                out_infos.append(info.copy())
        self._out_config = TensorsConfig(info=TensorsInfo(out_infos),
                                         rate=cfg.rate)
        self.announce_src_caps(caps_from_config(self._out_config))

    def _applies(self, idx: int) -> bool:
        return self._apply_idx is None or idx in self._apply_idx

    def _transform_info(self, info: TensorInfo) -> TensorInfo:
        mode = self._mode
        if mode == "typecast":
            return TensorInfo(self._out_type, info.dims, info.name)
        if mode == "arithmetic":
            dtype = info.dtype
            for op, operand, _ch in self._ops:
                if op == "typecast":
                    dtype = operand[0]
            return TensorInfo(dtype, info.dims, info.name)
        if mode == "transpose":
            if len(self._perm) < len(info.dims):
                raise ValueError(
                    f"{self.name}: transpose permutation rank "
                    f"{len(self._perm)} is below tensor rank "
                    f"{len(info.dims)} (dims {info.dims}) — a shorter "
                    "permutation would silently drop trailing dims")
            # reference transpose options are 4-index against NNS dims
            # padded with trailing 1s; pad, permute, strip the padding
            # back off (our dims convention is true-rank)
            padded = info.dims + (1,) * (len(self._perm) - len(info.dims))
            out = [padded[p] for p in self._perm]
            while len(out) > len(info.dims) and out[-1] == 1:
                out.pop()
            return TensorInfo(info.dtype, tuple(out), info.name)
        if mode == "dimchg":
            a, b = self._dimchg
            # same reference convention as transpose: indices address
            # NNS dims padded with trailing 1s (a verbatim '0:3' is
            # valid against a true-rank-3 tensor); pad, move, strip
            rank = max(len(info.dims), a + 1, b + 1)
            dims = list(info.dims) + [1] * (rank - len(info.dims))
            d = dims.pop(a)
            dims.insert(b, d)
            while len(dims) > len(info.dims) and dims[-1] == 1:
                dims.pop()
            return TensorInfo(info.dtype, tuple(dims), info.name)
        if mode == "stand":
            return TensorInfo(TensorType.FLOAT32, info.dims, info.name)
        return info.copy()  # clamp keeps type/shape

    # -- dataflow ------------------------------------------------------------
    def _apply(self, buf: TensorBuffer) -> TensorBuffer:
        outs = []
        for i in range(buf.num_tensors):
            t = buf.tensors[i]
            if not bool(self.acceleration) or isinstance(t, np.ndarray):
                t = buf.np(i)
            if self._applies(i):
                target = self._out_config.info[i].dtype
                outs.append(self._transform(t, target))
            else:
                outs.append(t)
        return buf.with_tensors(outs)

    def chain(self, pad, buf: TensorBuffer) -> FlowReturn:
        return self.push(self._apply(buf))

    def plan_step(self):
        return self._apply

    #: modes whose math is expressible as a pure jnp trace (the fuse=xla
    #: lowering set the ISSUE named; stand/clamp/transpose stay host-side
    #: for now and simply fall the segment back to fuse-python)
    _LOWERABLE_MODES = ("typecast", "arithmetic", "dimchg")

    def lower_reason(self):
        mode = str(self.mode or "")
        if mode not in self._LOWERABLE_MODES:
            return (f"tensor_transform mode={mode!r} has no jnp lowering "
                    f"(lowerable: {','.join(self._LOWERABLE_MODES)})")
        return None

    def lower_step(self):
        if self.lower_reason() is not None \
                or getattr(self, "_out_config", None) is None:
            return None
        # _transform is ALREADY jax-traceable for the lowerable modes:
        # under jit every input is a tracer, so _xp() picks jnp and the
        # per-channel writes take the ``.at`` branch — one math
        # implementation serves interpret, fuse-python and fuse-xla
        # (dtype caveat: the host path promotes uint8 arithmetic through
        # float64, the traced path through float32; identical after the
        # cast back for operands inside f32-exact range, see
        # docs/PERFORMANCE.md)
        n = self._out_config.info.num_tensors
        applies = [self._applies(i) for i in range(n)]
        targets = [self._out_config.info[i].dtype for i in range(n)]
        transform = self._transform

        def fn(params, ts, _applies=applies, _targets=targets,
               _tf=transform):
            return [_tf(t, _targets[i]) if _applies[i] else t
                    for i, t in enumerate(ts)]

        return LoweredStep(fn)

    def _transform(self, arr: Any, target=None) -> Any:
        xp = _xp(arr)
        mode = self._mode
        if mode == "typecast":
            return arr.astype(self._out_type.np_dtype)
        if mode == "arithmetic":
            out = arr
            for op, operand, applying_ch in self._ops:
                if op == "typecast":
                    out = out.astype(operand[0].np_dtype)
                    continue
                val = self._operand(operand, xp)
                if applying_ch >= 0 and self._ch_dim is not None:
                    # reference per-channel arithmetic: the op touches
                    # only index applying_ch along the NNS ch_dim axis
                    # (= numpy axis ndim-1-ch_dim), with the same
                    # padded-dims convention as transpose/dimchg: a
                    # ch_dim beyond the true rank addresses a padded
                    # size-1 axis, where channel 0 is the whole tensor
                    # and any other index never matches (the reference
                    # compares channel indices per element, so an
                    # out-of-range index is a no-op — made identical
                    # here on the numpy AND jnp paths)
                    if self._ch_dim >= out.ndim:
                        if applying_ch == 0:
                            new = (out + val if op == "add"
                                   else out * val if op == "mul"
                                   else out / val)
                            # match the in-range slice path, which
                            # writes back into the current dtype
                            out = (new.astype(out.dtype)
                                   if new.dtype != out.dtype else new)
                        continue
                    axis = out.ndim - 1 - self._ch_dim
                    if applying_ch >= out.shape[axis]:
                        continue
                    idx = [slice(None)] * out.ndim
                    idx[axis] = applying_ch
                    idx = tuple(idx)
                    sl = out[idx]
                    new = (sl + val if op == "add"
                           else sl * val if op == "mul" else sl / val)
                    if hasattr(out, "at"):          # jnp
                        out = out.at[idx].set(new)
                    else:
                        out = out.copy()
                        out[idx] = new
                elif op == "add":
                    out = out + val
                elif op == "mul":
                    out = out * val
                elif op == "div":
                    out = out / val
            # numpy promotion (e.g. uint8 + 0.5 → float64) must not leak
            # past the caps we announced: cast back to the negotiated dtype
            if target is not None and out.dtype != target.np_dtype:
                out = out.astype(target.np_dtype)
            return out
        if mode == "transpose":
            # reference dims are innermost-first; numpy axes are
            # reversed — and a 4-index reference option against a
            # lower-rank tensor pads with 1s (NNS trailing dims =
            # leading numpy axes), permutes, then strips the padding
            orig_ndim = arr.ndim
            nd = len(self._perm)
            if arr.ndim < nd:
                arr = arr.reshape((1,) * (nd - arr.ndim) + arr.shape)
            np_perm = tuple(nd - 1 - self._perm[nd - 1 - ax]
                            for ax in range(nd))
            out = xp.transpose(arr, np_perm)
            while out.ndim > orig_ndim and out.shape[0] == 1:
                out = out.reshape(out.shape[1:])
            return out
        if mode == "dimchg":
            a, b = self._dimchg
            orig_ndim = arr.ndim
            nd = max(arr.ndim, a + 1, b + 1)
            if arr.ndim < nd:
                arr = arr.reshape((1,) * (nd - arr.ndim) + arr.shape)
            out = xp.moveaxis(arr, nd - 1 - a, nd - 1 - b)
            while out.ndim > orig_ndim and out.shape[0] == 1:
                out = out.reshape(out.shape[1:])
            return out
        if mode == "stand":
            x = arr.astype(np.float32)
            axes = (tuple(range(x.ndim - 1)) if self._stand_per_channel
                    else None)
            mean = x.mean(axis=axes, keepdims=axes is not None)
            if self._stand_mode == "dc-average":
                return x - mean
            std = x.std(axis=axes, keepdims=axes is not None)
            return (x - mean) / (std + 1e-10)
        if mode == "clamp":
            lo, hi = self._clamp
            return xp.clip(arr, lo, hi)
        raise AssertionError(mode)

    @staticmethod
    def _operand(operand, xp):
        vals = operand
        if len(vals) == 1:
            return vals[0]
        # per-channel operand along the innermost reference dim = last np
        # axis; kept floating so fractional operands aren't truncated
        return xp.asarray(vals, dtype=np.float64 if xp is np else None)


def _parse_arith(option: str):
    """Parse the reference arithmetic option grammar
    (gsttensor_transform.c REGEX_ARITH_OPTION):
    ``[typecast:TYPE,][per-channel:(false|true@DIM),]
    add|mul|div:NUMBER[@CH_IDX], ...`` — plus this framework's
    multi-value per-channel operand extension ``add:1,2,3`` (values
    bind to the innermost dim).  Reference-verbatim behaviors honored:
    an UNKNOWN operator (``casttype:...``) warns and is skipped
    (GTT_OP_UNKNOWN — the ssat goldens rely on the pipeline running
    with the op dropped), and extra ``:NUMBER`` segments after the
    first operand are accepted-and-ignored (the reference regex admits
    them, its parser reads only values[0]).

    Returns ``(ops, ch_dim)``: ops as ``(op, operand, applying_ch)``
    triples (-1 = all channels), ch_dim the per-channel NNS dim index
    or None."""
    ops: List[Tuple[str, Any, int]] = []
    ch_dim = None
    # break before any "word:" token (op names and per-channel alike);
    # numeric per-channel value lists keep their commas
    parts = re.split(r",(?=[a-z-]+:)", option)
    for part in parts:
        if not part.strip():
            continue
        op, _, val = part.partition(":")
        op = op.strip()
        if op == "per-channel":
            flag, _, dim = val.partition("@")
            if flag.strip().lower() == "true":
                ch_dim = int(dim) if dim.strip() else 0
            continue
        if op == "typecast":
            ops.append((op, [TensorType.from_string(val)], -1))
        elif op in ("add", "mul", "div", "sub"):
            val, _, ch = val.partition("@")
            applying_ch = int(ch) if ch.strip() else -1
            vals = []
            for item in val.split(","):
                segs = item.split(":")
                if len(segs) > 1:
                    ml_logw("arithmetic %s: ignoring extra operand "
                            "segments %s (reference parser reads only "
                            "the first)", op, segs[1:])
                vals.append(float(segs[0]))
            if op == "sub":
                op, vals = "add", [-v for v in vals]
            if applying_ch >= 0 and len(vals) > 1:
                # a multi-value operand binds to the innermost dim; a
                # single-channel selector makes that a shape mismatch,
                # so keep the first value (and say so) instead of
                # deferring to a numpy broadcast crash mid-stream
                ml_logw("arithmetic %s@%d: multi-value operand %s "
                        "reduced to its first value (per-channel "
                        "selector takes one operand)", op, applying_ch,
                        vals)
                vals = vals[:1]
            ops.append((op, vals, applying_ch))
        else:
            # reference GTT_OP_UNKNOWN: warn and drop the op, keep the
            # pipeline running (ssat tests pass casttype:... expecting
            # exactly this)
            ml_logw("arithmetic: unknown operator %r skipped "
                    "(reference GTT_OP_UNKNOWN behavior)", op)
    return ops, ch_dim
