"""tensor_if: data-dependent stream routing.

Parity with gst/nnstreamer/elements/gsttensor_if.c (enums at
gsttensor_if.h:42-141): a compared value (per-tensor value / tensor
average / custom callback) tested with an operator against supplied
operand(s) routes each buffer to the ``then`` or ``else`` behavior:
passthrough, skip, fill-zero, or tensorpick on two src pads (src_0 = then,
src_1 = else when both linked).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..pipeline.element import Element, FlowReturn, Pad
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import static_tensors_caps

_OPS = {
    "eq": lambda v, a, b: v == a,
    "ne": lambda v, a, b: v != a,
    "gt": lambda v, a, b: v > a,
    "ge": lambda v, a, b: v >= a,
    "lt": lambda v, a, b: v < a,
    "le": lambda v, a, b: v <= a,
    "range-inclusive": lambda v, a, b: a <= v <= b,
    "range-exclusive": lambda v, a, b: a < v < b,
    "not-in-range-inclusive": lambda v, a, b: not (a <= v <= b),
    "not-in-range-exclusive": lambda v, a, b: not (a < v < b),
}

_CUSTOM_CONDS: dict = {}


def _norm(value, aliases: Optional[dict] = None) -> str:
    """Reference launch lines spell tensor_if enum values in
    UPPER_SNAKE (compared-value=A_VALUE operator=RANGE_INCLUSIVE
    then=PASSTHROUGH — every ssat script does); normalize to this
    module's lower-hyphen names so verbatim lines run."""
    k = str(value).strip().lower().replace("_", "-")
    return (aliases or {}).get(k, k)


#: reference nick → this module's name, post-normalization
_CV_ALIASES = {"tensor-average-value": "tensor-average"}
_BEHAVIOR_ALIASES = {"fill-with-zero": "fill-zero"}


def register_if_custom(name: str, fn: Callable[[TensorBuffer], bool]) -> None:
    """Custom condition callback (reference tensor_if.h custom API)."""
    _CUSTOM_CONDS[name] = fn


@register_element
class TensorIf(Element):
    FACTORY = "tensor_if"
    PROPERTIES = {
        "compared-value": ("a-value", "a-value|tensor-average|custom"),
        "compared-value-option": (None, "e.g. '0:0:0:0,0' index or tensor idx"),
        "supplied-value": (None, "operand(s), comma separated"),
        "operator": ("gt", "|".join(_OPS)),
        "then": ("passthrough", "passthrough|skip|fill-zero|tensorpick"),
        "then-option": (None, "tensorpick indices"),
        "else": ("skip", "passthrough|skip|fill-zero|tensorpick"),
        "else-option": (None, "tensorpick indices"),
    }

    def _make_pads(self):
        self.add_sink_pad(static_tensors_caps(), "sink")
        self.add_src_pad(static_tensors_caps(), "src_0")

    def request_src_pad(self) -> Pad:
        if len(self.src_pads) >= 2:
            raise ValueError("tensor_if has at most 2 src pads")
        return self.add_src_pad(static_tensors_caps(), "src_1")

    def start(self):
        # enum spellings resolve ONCE here (the chain() hot path must
        # not re-normalize per buffer), and bad spellings fail the
        # pipeline at start, not mid-stream
        op = _norm(self.operator)
        if op not in _OPS:
            raise ValueError(f"unknown operator {self.operator}")
        self._op = _OPS[op]
        self._cv = _norm(self.compared_value, _CV_ALIASES)
        if self._cv not in ("a-value", "tensor-average", "custom"):
            raise ValueError(
                f"unknown compared-value {self.compared_value!r}")
        self._then = _norm(self.then, _BEHAVIOR_ALIASES)
        self._else = _norm(getattr(self, "else"), _BEHAVIOR_ALIASES)
        for raw, b in ((self.then, self._then),
                       (getattr(self, "else"), self._else)):
            if b not in ("passthrough", "skip", "fill-zero",
                         "tensorpick"):
                raise ValueError(f"unknown behavior {raw!r}")
        sup = str(self.supplied_value or "0")
        vals = [float(x) for x in sup.split(",")]
        self._a = vals[0]
        self._b = vals[1] if len(vals) > 1 else vals[0]

    def set_property(self, key, value):
        super().set_property(key, value)
        # properties stay runtime-mutable (GObject semantics): a set
        # on a PLAYING element re-resolves the enum snapshot start()
        # froze for the hot path
        if hasattr(self, "_op") and key in (
                "operator", "compared-value", "then", "else",
                "supplied-value"):
            self.start()

    def _compared_value(self, buf: TensorBuffer) -> float:
        cv = self._cv
        opt = self.compared_value_option
        if cv == "custom":
            fn = _CUSTOM_CONDS.get(str(opt))
            if fn is None:
                raise ValueError(f"custom condition {opt!r} not registered")
            return fn(buf)
        if cv == "tensor-average":
            idx = int(opt) if opt not in (None, "") else 0
            return float(np.mean(buf.np(idx)))
        # a-value: "i0:i1:...,tensor_idx" picks one element
        if opt in (None, ""):
            return float(np.ravel(buf.np(0))[0])
        coord_s, _, tidx = str(opt).partition(",")
        tensor = buf.np(int(tidx) if tidx else 0)
        coords = tuple(int(x) for x in coord_s.split(":"))
        # reference coords are innermost-first; numpy index is reversed
        np_idx = tuple(reversed(coords))[-tensor.ndim:]
        return float(tensor[np_idx])

    def _apply_behavior(self, behavior: str, option, buf: TensorBuffer
                        ) -> Optional[TensorBuffer]:
        if behavior == "passthrough":
            return buf
        if behavior == "skip":
            return None
        if behavior == "fill-zero":
            return buf.with_tensors(
                [np.zeros_like(buf.np(i)) for i in range(buf.num_tensors)])
        if behavior == "tensorpick":
            picks = [int(x) for x in str(option).split(",")]
            return buf.with_tensors([buf.tensors[i] for i in picks])
        raise ValueError(f"unknown behavior {behavior!r}")

    def chain(self, pad, buf):
        v = self._compared_value(buf)
        cond = bool(self._op(v, self._a, self._b))
        if cond:
            out = self._apply_behavior(self._then, self.then_option, buf)
            target = self.src_pads[0]
        else:
            out = self._apply_behavior(self._else, self.else_option, buf)
            target = (self.src_pads[1] if len(self.src_pads) > 1
                      else self.src_pads[0])
        if out is None:
            return FlowReturn.DROPPED
        return target.push(out)

    def set_caps(self, pad, caps):
        from ..pipeline.element import CapsEvent
        from ..tensor.caps_util import caps_from_config, config_from_caps
        from ..tensor.info import TensorsConfig, TensorsInfo

        cfg = config_from_caps(caps)
        behaviors = [(self._then, self.then_option),
                     (self._else, self.else_option)]
        for sp, (behavior, option) in zip(self.src_pads, behaviors):
            if behavior == "tensorpick" and cfg.info.num_tensors:
                picks = [int(x) for x in str(option).split(",")]
                out = TensorsConfig(
                    info=TensorsInfo([cfg.info[i].copy() for i in picks]),
                    rate=cfg.rate)
                sp.push_event(CapsEvent(caps_from_config(out)))
            else:
                sp.push_event(CapsEvent(caps))
