"""tensor_src_iio: Linux Industrial I/O sensor source.

Parity with gst/nnstreamer/elements/gsttensor_srciio.c (struct
gsttensor_srciio.h:52-131): scans an IIO device's sysfs tree for enabled
scan-element channels, reads samples, applies per-channel scale/offset, and
emits float tensors.  The reference's test strategy — a simulated sysfs
device tree (tests/nnstreamer_source/unittest_src_iio.cc) — is mirrored by
the ``base-dir`` property pointing at any directory laid out like
``/sys/bus/iio/devices``.

Two capture modes, mirroring the reference's:

- ``mode=poll`` (one-shot role): polls the sysfs ``in_*_raw`` text values
  at the negotiated rate.
- ``mode=buffer`` (triggered/continuous role, gsttensor_srciio.c buffered
  engine): configures the trigger (``trigger/current_trigger``), enables
  the ``scan_elements`` channels (``in_*_en``), parses each channel's
  binary layout from its ``in_*_type`` spec (``le:s12/16>>4`` —
  endianness, sign, realbits/storagebits, shift), sets ``buffer/length``,
  enables the buffer, and reads packed binary sample frames from the
  device chardev with endian conversion, shift, sign-extension and
  scale/offset applied per channel.

The ``base-dir``/``dev-dir`` properties point the sysfs tree and chardev
directory at a simulated layout for tests, exactly the reference's
simulated-device-tree strategy (tests/nnstreamer_source/
unittest_src_iio.cc).
"""

from __future__ import annotations

import os
import time
from fractions import Fraction
from typing import Dict, List, Optional

import numpy as np

from ..pipeline.caps import Caps
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import SECOND, TensorBuffer
from ..tensor.caps_util import caps_from_config, static_tensors_caps
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensor.types import TensorType

DEFAULT_BASE_DIR = "/sys/bus/iio/devices"


def parse_type_spec(spec: str) -> Dict:
    """Parse an IIO scan-element type spec like ``le:s12/16>>4`` into
    (endian, signed, realbits, storagebits, shift) — the reference's
    gst_tensor_src_iio_get_channel_type parsing."""
    endian, _, rest = spec.strip().partition(":")
    if endian not in ("le", "be"):
        raise ValueError(f"iio: bad type spec {spec!r} (endian)")
    signed = rest[:1]
    if signed not in ("s", "u"):
        raise ValueError(f"iio: bad type spec {spec!r} (sign)")
    bits_part, _, shift_part = rest[1:].partition(">>")
    real_s, _, storage_s = bits_part.partition("/")
    real = int(real_s)
    storage = int(storage_s or real_s)
    if storage not in (8, 16, 32, 64):
        raise ValueError(f"iio: unsupported storagebits {storage}")
    if real > storage:
        raise ValueError(f"iio: realbits {real} > storagebits {storage}")
    return {"endian": endian, "signed": signed == "s", "realbits": real,
            "storagebits": storage,
            "shift": int(shift_part) if shift_part else 0}


def extract_sample(raw: int, spec: Dict) -> int:
    """Shift + mask + sign-extend one storage word (reference
    gst_tensor_src_iio_process_scanned_data)."""
    v = (raw >> spec["shift"]) & ((1 << spec["realbits"]) - 1)
    if spec["signed"] and v & (1 << (spec["realbits"] - 1)):
        v -= 1 << spec["realbits"]
    return v


@register_element
class TensorSrcIIO(Source):
    FACTORY = "tensor_src_iio"
    PROPERTIES = {
        "device": (None, "IIO device name (matches <dev>/name)"),
        "device-number": (-1, "or explicit iio:deviceN number"),
        "base-dir": (DEFAULT_BASE_DIR, "sysfs root (tests point this at a "
                                       "simulated tree)"),
        "dev-dir": ("/dev", "chardev directory for mode=buffer (tests "
                            "point this at a simulated one)"),
        "mode": ("poll", "poll (sysfs one-shot) | buffer (triggered "
                         "chardev capture)"),
        "trigger": (None, "trigger name to write to current_trigger "
                          "(mode=buffer)"),
        "buffer-capacity": (1, "samples per emitted tensor AND the value "
                               "written to buffer/length (mode=buffer)"),
        "frequency": (10, "sampling frequency Hz"),
        "num-buffers": (-1, "samples to emit, -1 unlimited"),
        "merge-channels": (True, "one tensor of all channels vs per-channel"),
    }

    def _make_pads(self):
        self.add_src_pad(static_tensors_caps(), "src")

    def start(self):
        base = str(self.base_dir)
        self._dev_dir = self._find_device(base)
        self._count = 0
        self._pace_origin_ns = None   # first-sample monotonic anchor
        self._chardev = None
        if str(self.mode) == "buffer":
            self._channels = self._scan_buffer_channels(self._dev_dir)
            if not self._channels:
                raise ValueError(
                    f"{self.name}: no scan_elements in {self._dev_dir}")
            self._setup_buffer_capture()
        else:
            self._channels = self._scan_channels(self._dev_dir)
            if not self._channels:
                raise ValueError(
                    f"{self.name}: no channels in {self._dev_dir}")

    def stop(self):
        if self._chardev is not None:
            try:
                self._chardev.close()
            except OSError:
                pass
            self._chardev = None
            # disable the buffer on teardown (reference stop path)
            self._write_sysfs(os.path.join(self._dev_dir, "buffer",
                                           "enable"), "0")
        super().stop()

    def _find_device(self, base: str) -> str:
        if not os.path.isdir(base):
            raise ValueError(f"{self.name}: no IIO tree at {base}")
        want_num = int(self.device_number)
        want_name = self.device
        for entry in sorted(os.listdir(base)):
            if not entry.startswith("iio:device"):
                continue
            path = os.path.join(base, entry)
            if want_num >= 0 and entry == f"iio:device{want_num}":
                return path
            if want_name:
                name_file = os.path.join(path, "name")
                if os.path.exists(name_file):
                    with open(name_file) as f:
                        if f.read().strip() == str(want_name):
                            return path
        raise ValueError(
            f"{self.name}: device {want_name or want_num!r} not found "
            f"under {base}")

    def _scan_channels(self, dev_dir: str) -> List[Dict]:
        """Channels = in_*_raw files, with optional *_scale / *_offset
        (reference channel scan over scan_elements)."""
        chans = []
        for fname in sorted(os.listdir(dev_dir)):
            if fname.startswith("in_") and fname.endswith("_raw"):
                stem = fname[:-4]  # in_voltage0
                chans.append({
                    "name": stem,
                    "raw": os.path.join(dev_dir, fname),
                    "scale": self._read_float(
                        os.path.join(dev_dir, stem + "_scale"), 1.0),
                    "offset": self._read_float(
                        os.path.join(dev_dir, stem + "_offset"), 0.0),
                })
        return chans

    @staticmethod
    def _read_float(path: str, default: float) -> float:
        """Missing file → default (IIO semantics: absent *_scale means raw
        units).  A PRESENT but malformed file is a broken device tree —
        warn instead of silently normalizing with the default."""
        try:
            with open(path) as f:
                text = f.read().strip()
        except OSError:
            return default
        try:
            return float(text)
        except ValueError:
            from ..utils.log import ml_logw

            ml_logw("srciio: malformed sysfs float %s=%r; using %s",
                    path, text, default)
            return default

    def _write_sysfs(self, path: str, value: str) -> bool:
        """Write a sysfs control file; missing files are reported (the
        round-1 silent-fallback gap), not fatal — simulated trees may omit
        controls the real kernel always has."""
        try:
            with open(path, "w") as f:
                f.write(value)
            return True
        except OSError as e:
            from ..utils.log import logger

            logger.warning("%s: cannot write %s=%s: %s", self.name, path,
                           value, e)
            return False

    # -- buffered/triggered capture (reference gsttensor_srciio.c engine) ----
    def _scan_buffer_channels(self, dev_dir: str) -> List[Dict]:
        """Scan ``scan_elements``: per channel the _type layout spec,
        _index byte order, and _en enable switch (which we turn on, like
        the reference's channel-enable writes)."""
        se_dir = os.path.join(dev_dir, "scan_elements")
        if not os.path.isdir(se_dir):
            raise ValueError(f"{self.name}: mode=buffer but no "
                             f"scan_elements dir in {dev_dir}")
        chans = []
        for fname in sorted(os.listdir(se_dir)):
            if not fname.endswith("_type") or not fname.startswith("in_"):
                continue
            stem = fname[:-5]                       # in_voltage0
            with open(os.path.join(se_dir, fname)) as f:
                spec = parse_type_spec(f.read())
            idx_path = os.path.join(se_dir, stem + "_index")
            try:
                with open(idx_path) as f:
                    index = int(f.read().strip())
            except (OSError, ValueError):
                index = len(chans)
            chans.append({
                "name": stem, "spec": spec, "index": index,
                "en": os.path.join(se_dir, stem + "_en"),
                "scale": self._read_float(
                    os.path.join(dev_dir, stem + "_scale"), 1.0),
                "offset": self._read_float(
                    os.path.join(dev_dir, stem + "_offset"), 0.0),
            })
        chans.sort(key=lambda c: c["index"])
        return chans

    def _setup_buffer_capture(self) -> None:
        # 1. enable every scan channel (reference enables the channel set)
        for c in self._channels:
            self._write_sysfs(c["en"], "1")
        # 2. configure the trigger when given
        if self.trigger:
            self._write_sysfs(
                os.path.join(self._dev_dir, "trigger", "current_trigger"),
                str(self.trigger))
        # 3. buffer length then enable (reference ordering)
        cap = max(int(self.buffer_capacity), 1)
        self._write_sysfs(os.path.join(self._dev_dir, "buffer", "length"),
                          str(cap))
        self._write_sysfs(os.path.join(self._dev_dir, "buffer", "enable"),
                          "1")
        # 4. open the chardev — on failure disable the buffer again so the
        # kernel is not left capturing (a retry would then hit EBUSY on
        # the channel-enable writes)
        dev_name = os.path.basename(self._dev_dir)
        path = os.path.join(str(self.dev_dir), dev_name)
        try:
            self._chardev = open(path, "rb", buffering=0)
        except OSError as e:
            self._write_sysfs(os.path.join(self._dev_dir, "buffer",
                                           "enable"), "0")
            raise ValueError(f"{self.name}: cannot open chardev {path}: "
                             f"{e}") from e
        # packed frame layout: channels at storage-size alignment, in
        # index order (reference scan-element frame geometry)
        off = 0
        for c in self._channels:
            size = c["spec"]["storagebits"] // 8
            off = (off + size - 1) // size * size   # natural alignment
            c["byte_off"] = off
            off += size
        self._frame_bytes = off

    def _read_exact(self, n: int) -> Optional[bytes]:
        out = b""
        while len(out) < n and not self._halted.is_set():
            chunk = self._chardev.read(n - len(out))
            if not chunk:
                return out if out else None
            out += chunk
        return out if len(out) == n else None

    def _create_buffered(self) -> Optional[np.ndarray]:
        """Read buffer-capacity packed frames from the chardev and decode
        to a (capacity, channels) float array."""
        cap = max(int(self.buffer_capacity), 1)
        blob = self._read_exact(self._frame_bytes * cap)
        if blob is None:
            return None
        cap = len(blob) // self._frame_bytes
        if cap == 0:
            return None
        mat8 = np.frombuffer(blob[:cap * self._frame_bytes],
                             np.uint8).reshape(cap, self._frame_bytes)
        out = np.empty((cap, len(self._channels)), np.float32)
        for j, c in enumerate(self._channels):
            spec = c["spec"]
            size = spec["storagebits"] // 8
            dt = np.dtype(f"{'<' if spec['endian'] == 'le' else '>'}u{size}")
            words = mat8[:, c["byte_off"]:c["byte_off"] + size] \
                .copy().view(dt).reshape(-1).astype(np.int64)
            v = (words >> spec["shift"]) & ((1 << spec["realbits"]) - 1)
            if spec["signed"]:
                sign_bit = 1 << (spec["realbits"] - 1)
                v = np.where(v & sign_bit, v - (1 << spec["realbits"]), v)
            out[:, j] = (v + c["offset"]) * c["scale"]
        return out

    def negotiate(self) -> Caps:
        n = len(self._channels)
        buffered = str(self.mode) == "buffer"
        cap = max(int(self.buffer_capacity), 1) if buffered else 1
        # caps rate is the BUFFER cadence: capacity samples coalesce into
        # one buffer, so downstream sees frequency/capacity frames per sec
        rate = Fraction(int(self.frequency), cap)
        if bool(self.merge_channels):
            # innermost-first dims: (channels, capacity) numpy shape →
            # reference dim string channels:capacity
            shape = (cap, n) if cap > 1 else (n,)
            info = TensorsInfo([TensorInfo(TensorType.FLOAT32, shape)])
        else:
            shape = (cap, 1) if cap > 1 else (1,)
            info = TensorsInfo([TensorInfo(TensorType.FLOAT32, shape,
                                           name=c["name"])
                                for c in self._channels])
        self._config = TensorsConfig(info=info, rate=rate)
        return caps_from_config(self._config)

    def create(self) -> Optional[TensorBuffer]:
        limit = int(self.num_buffers)
        if limit >= 0 and self._count >= limit:
            return None
        freq = max(int(self.frequency), 1)
        if str(self.mode) == "buffer":
            mat = self._create_buffered()     # (capacity, channels)
            if mat is None:
                return None
            cap = max(int(self.buffer_capacity), 1)
            if mat.shape[0] < cap:            # short final read: pad-free EOS
                return None
            if cap == 1:
                mat = mat[0]
            if bool(self.merge_channels):
                tensors = [mat]
            else:
                tensors = [mat[..., i:i + 1] for i in
                           range(len(self._channels))]
            pts = self._count * cap * SECOND // freq
            buf = TensorBuffer(tensors=tensors, pts=pts,
                               duration=cap * SECOND // freq)
            self._count += 1
            return buf
        values = []
        for c in self._channels:
            raw = self._read_float(c["raw"], 0.0)
            values.append((raw + c["offset"]) * c["scale"])
        arr = np.asarray(values, np.float32)
        pts = self._count * SECOND // freq
        if bool(self.merge_channels):
            tensors = [arr]
        else:
            tensors = [arr[i:i + 1] for i in range(len(values))]
        buf = TensorBuffer(tensors=tensors, pts=pts,
                           duration=SECOND // freq)
        self._count += 1
        # pace to the requested frequency against an ABSOLUTE monotonic
        # deadline ladder: relative time.sleep(1/freq) drifts by the
        # per-sample processing time (so rate metrics read low), and a
        # plain sleep is uncancellable — the event wait returns the
        # moment stop() sets _halted, and a late sample shortens the
        # next wait instead of pushing every later deadline out
        if (limit < 0 or self._count < limit) and freq < 1000:
            if self._pace_origin_ns is None:
                self._pace_origin_ns = time.monotonic_ns()
            deadline_ns = (self._pace_origin_ns
                           + self._count * SECOND // freq)
            wait_s = (deadline_ns - time.monotonic_ns()) / 1e9
            if wait_s > 0:
                self._halted.wait(wait_s)
        return buf
