"""tensor_src_iio: Linux Industrial I/O sensor source.

Parity with gst/nnstreamer/elements/gsttensor_srciio.c (struct
gsttensor_srciio.h:52-131): scans an IIO device's sysfs tree for enabled
scan-element channels, reads samples, applies per-channel scale/offset, and
emits float tensors.  The reference's test strategy — a simulated sysfs
device tree (tests/nnstreamer_source/unittest_src_iio.cc) — is mirrored by
the ``base-dir`` property pointing at any directory laid out like
``/sys/bus/iio/devices``.

Simplifications vs the reference (documented divergence): buffered
trigger/chardev capture is replaced by polling the sysfs ``in_*_raw``
values at the negotiated rate; endian/packing variants of scan elements are
not needed because sysfs raw reads are text.
"""

from __future__ import annotations

import os
import time
from fractions import Fraction
from typing import Dict, List, Optional

import numpy as np

from ..pipeline.caps import Caps
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import SECOND, TensorBuffer
from ..tensor.caps_util import caps_from_config, static_tensors_caps
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensor.types import TensorType

DEFAULT_BASE_DIR = "/sys/bus/iio/devices"


@register_element
class TensorSrcIIO(Source):
    FACTORY = "tensor_src_iio"
    PROPERTIES = {
        "device": (None, "IIO device name (matches <dev>/name)"),
        "device-number": (-1, "or explicit iio:deviceN number"),
        "base-dir": (DEFAULT_BASE_DIR, "sysfs root (tests point this at a "
                                       "simulated tree)"),
        "frequency": (10, "sampling frequency Hz"),
        "num-buffers": (-1, "samples to emit, -1 unlimited"),
        "merge-channels": (True, "one tensor of all channels vs per-channel"),
    }

    def _make_pads(self):
        self.add_src_pad(static_tensors_caps(), "src")

    def start(self):
        base = str(self.base_dir)
        self._dev_dir = self._find_device(base)
        self._channels = self._scan_channels(self._dev_dir)
        if not self._channels:
            raise ValueError(f"{self.name}: no channels in {self._dev_dir}")
        self._count = 0

    def _find_device(self, base: str) -> str:
        if not os.path.isdir(base):
            raise ValueError(f"{self.name}: no IIO tree at {base}")
        want_num = int(self.device_number)
        want_name = self.device
        for entry in sorted(os.listdir(base)):
            if not entry.startswith("iio:device"):
                continue
            path = os.path.join(base, entry)
            if want_num >= 0 and entry == f"iio:device{want_num}":
                return path
            if want_name:
                name_file = os.path.join(path, "name")
                if os.path.exists(name_file):
                    with open(name_file) as f:
                        if f.read().strip() == str(want_name):
                            return path
        raise ValueError(
            f"{self.name}: device {want_name or want_num!r} not found "
            f"under {base}")

    def _scan_channels(self, dev_dir: str) -> List[Dict]:
        """Channels = in_*_raw files, with optional *_scale / *_offset
        (reference channel scan over scan_elements)."""
        chans = []
        for fname in sorted(os.listdir(dev_dir)):
            if fname.startswith("in_") and fname.endswith("_raw"):
                stem = fname[:-4]  # in_voltage0
                chans.append({
                    "name": stem,
                    "raw": os.path.join(dev_dir, fname),
                    "scale": self._read_float(
                        os.path.join(dev_dir, stem + "_scale"), 1.0),
                    "offset": self._read_float(
                        os.path.join(dev_dir, stem + "_offset"), 0.0),
                })
        return chans

    @staticmethod
    def _read_float(path: str, default: float) -> float:
        try:
            with open(path) as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return default

    def negotiate(self) -> Caps:
        n = len(self._channels)
        rate = Fraction(int(self.frequency), 1)
        if bool(self.merge_channels):
            info = TensorsInfo([TensorInfo(TensorType.FLOAT32, (n,))])
        else:
            info = TensorsInfo([TensorInfo(TensorType.FLOAT32, (1,),
                                           name=c["name"])
                                for c in self._channels])
        self._config = TensorsConfig(info=info, rate=rate)
        return caps_from_config(self._config)

    def create(self) -> Optional[TensorBuffer]:
        limit = int(self.num_buffers)
        if limit >= 0 and self._count >= limit:
            return None
        values = []
        for c in self._channels:
            raw = self._read_float(c["raw"], 0.0)
            values.append((raw + c["offset"]) * c["scale"])
        arr = np.asarray(values, np.float32)
        freq = max(int(self.frequency), 1)
        pts = self._count * SECOND // freq
        if bool(self.merge_channels):
            tensors = [arr]
        else:
            tensors = [arr[i:i + 1] for i in range(len(values))]
        buf = TensorBuffer(tensors=tensors, pts=pts,
                           duration=SECOND // freq)
        self._count += 1
        # pace to the requested frequency (reference polls at trigger rate)
        if limit < 0 or self._count < limit:
            time.sleep(1.0 / freq if freq < 1000 else 0)
        return buf
