"""tensor_converter: media streams → other/tensors.

Parity with gst/nnstreamer/elements/gsttensor_converter.c (chain at
:1015-1300): accepts video/audio/text/octet/flexible-tensor input, emits
static tensors, with frames-per-tensor batching.  Differences by design:

- media buffers in this framework are already ndarray-backed (no stride-4
  row padding to strip — the reference's memcpy unpadding at :1062-1107 has
  no equivalent because our video frames are dense arrays);
- frame accumulation uses a simple list instead of GstAdapter.

Converter *subplugins* (flatbuf/protobuf/… of §2.6) register via
:mod:`nnstreamer_tpu.converters`.
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional

import numpy as np

from ..pipeline.caps import ANY_FRAMERATE, Caps, Structure
from ..pipeline.element import CapsEvent, Element, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer
from ..tensor.caps_util import caps_from_config, flexible_tensors_caps
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensor.meta import TensorMetaInfo
from ..tensor.types import (TensorFormat, TensorType, dim_parse,
                            np_shape_to_dim)
from .src import VIDEO_FORMATS, _CHANNELS, video_template_caps

_AUDIO_TYPES = {"S8": TensorType.INT8, "U8": TensorType.UINT8,
                "S16LE": TensorType.INT16, "U16LE": TensorType.UINT16,
                "S32LE": TensorType.INT32, "U32LE": TensorType.UINT32,
                "F32LE": TensorType.FLOAT32, "F64LE": TensorType.FLOAT64}


@register_element
class TensorConverter(Element):
    FACTORY = "tensor_converter"
    PROPERTIES = {
        "frames-per-tensor": (1, "frames batched into one tensor"),
        "input-dim": (None, "forced dim for octet streams"),
        "input-type": (None, "forced type for octet streams"),
        "set-timestamp": (True, "synthesize PTS when absent"),
        "mode": (None, "custom converter subplugin: 'custom-code:<name>'"),
    }

    def _make_pads(self):
        sink_tmpl = (video_template_caps()
                     .append(Caps([Structure("audio/x-raw", {})]))
                     .append(Caps([Structure("text/x-raw", {})]))
                     .append(Caps([Structure("application/octet-stream", {})]))
                     .append(flexible_tensors_caps()))
        self.add_sink_pad(sink_tmpl, "sink")
        from ..tensor.caps_util import tensors_template_caps

        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        self._pending: List[np.ndarray] = []
        self._pending_pts: Optional[int] = None
        self._out_config: Optional[TensorsConfig] = None
        self._media: Optional[str] = None
        self._custom = None
        mode = self.mode
        if mode:
            kind, _, name = str(mode).partition(":")
            from ..converters import find_converter

            self._custom = find_converter(name)

    # -- negotiation ---------------------------------------------------------
    def set_caps(self, pad, caps):
        st = caps.first()
        self._media = st.name
        fpt = int(self.frames_per_tensor)
        rate = st.get("framerate")
        if isinstance(rate, Fraction) and fpt > 1:
            rate = rate / fpt
        if self._custom is not None:
            cfg = self._custom.get_out_config(caps)
        elif st.name == "video/x-raw":
            w, h = int(st.get("width")), int(st.get("height"))
            fmt = str(st.get("format"))
            ch = _CHANNELS[fmt]
            dims = (ch, w, h) if fpt == 1 else (ch, w, h, fpt)
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(TensorType.UINT8, dims)]),
                rate=rate if isinstance(rate, Fraction) else Fraction(30, 1))
        elif st.name == "audio/x-raw":
            fmt = str(st.get("format", "S16LE"))
            dtype = _AUDIO_TYPES.get(fmt)
            if dtype is None:
                raise ValueError(f"unsupported audio format {fmt}")
            ch = int(st.get("channels", 1))
            self._audio_dtype = dtype
            # per-buffer sample count varies; negotiated lazily on first buf
            self._audio_channels = ch
            self._audio_rate = rate if isinstance(rate, Fraction) else None
            self._out_config = None
            return  # announce on first buffer
        elif st.name == "text/x-raw":
            dim = dim_parse(str(self.input_dim)) if self.input_dim else (256,)
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(TensorType.UINT8, dim)]),
                rate=rate if isinstance(rate, Fraction) else Fraction(0, 1))
        elif st.name == "application/octet-stream":
            if not self.input_dim or not self.input_type:
                raise ValueError(
                    "octet stream requires input-dim and input-type")
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(
                    TensorType.from_string(str(self.input_type)),
                    dim_parse(str(self.input_dim)))]),
                rate=rate if isinstance(rate, Fraction) else Fraction(0, 1))
        elif st.name == "other/tensors":  # flexible → static promotion
            self._out_config = None
            return  # per-buffer meta decides; announced on first buffer
        else:
            raise ValueError(f"unsupported media type {st.name}")
        self._announce(cfg)

    def _announce(self, cfg: TensorsConfig) -> None:
        self._out_config = cfg
        self.announce_src_caps(caps_from_config(cfg))

    # -- dataflow ------------------------------------------------------------
    def chain(self, pad, buf: TensorBuffer) -> FlowReturn:
        if self._custom is not None:
            out = self._custom.convert(buf)
            return self.push(out)
        media = self._media
        if media == "video/x-raw":
            return self._chain_video(buf)
        if media == "audio/x-raw":
            return self._chain_audio(buf)
        if media in ("text/x-raw", "application/octet-stream"):
            return self._chain_bytes(buf)
        if media == "other/tensors":
            return self._chain_flex(buf)
        raise RuntimeError(f"no caps negotiated on {self.name}")

    def _chain_video(self, buf: TensorBuffer) -> FlowReturn:
        frame = buf.np(0)
        fpt = int(self.frames_per_tensor)
        if fpt == 1:
            return self.push(buf.with_tensors([frame]))
        # accumulate frames → one tensor of dims (c,w,h,fpt)
        self._pending.append(frame)
        if self._pending_pts is None:
            self._pending_pts = buf.pts
        if len(self._pending) < fpt:
            return FlowReturn.OK
        stacked = np.stack(self._pending, axis=0)  # (fpt,h,w,c)
        self._pending = []
        out = TensorBuffer(tensors=[stacked], pts=self._pending_pts,
                           duration=(buf.duration or 0) * fpt)
        self._pending_pts = None
        return self.push(out)

    def _chain_audio(self, buf: TensorBuffer) -> FlowReturn:
        samples = buf.np(0)
        if self._out_config is None:
            dims = np_shape_to_dim(samples.shape)
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(self._audio_dtype, dims)]),
                rate=self._audio_rate or Fraction(0, 1))
            self._announce(cfg)
        return self.push(buf.with_tensors([samples]))

    def _chain_bytes(self, buf: TensorBuffer) -> FlowReturn:
        info = self._out_config.info[0]
        raw = np.asarray(buf.np(0)).reshape(-1).view(np.uint8)
        want = info.size
        if raw.nbytes < want:  # pad (reference text pad/clip :1114-1143)
            raw = np.concatenate(
                [raw, np.zeros(want - raw.nbytes, np.uint8)])
        raw = raw[:want]
        arr = raw.view(info.np_dtype).reshape(info.np_shape)
        return self.push(buf.with_tensors([arr]))

    def _chain_flex(self, buf: TensorBuffer) -> FlowReturn:
        """Flexible → static promotion: first buffer's meta fixes the config
        (reference :1155-1200)."""
        if self._out_config is None:
            infos = []
            for i in range(buf.num_tensors):
                meta = (buf.metas[i] if buf.metas else
                        TensorMetaInfo.from_info(
                            TensorInfo.from_np(buf.np(i))))
                infos.append(meta.to_info())
            cfg = TensorsConfig(info=TensorsInfo(infos), rate=Fraction(0, 1))
            self._announce(cfg)
        for i, info in enumerate(self._out_config.info):
            got = np_shape_to_dim(buf.np(i).shape)
            if not TensorInfo(info.dtype, got).is_equal(info):
                raise ValueError(
                    f"flexible stream changed shape: {got} != {info.dims}")
        return self.push(buf.with_tensors(
            [buf.np(i) for i in range(buf.num_tensors)]))
