"""tensor_converter: media streams → other/tensors.

Parity with gst/nnstreamer/elements/gsttensor_converter.c (chain at
:1015-1300): accepts video/audio/text/octet/flexible-tensor input, emits
static tensors, with frames-per-tensor batching.  Differences by design:

- media buffers in this framework are already ndarray-backed (no stride-4
  row padding to strip — the reference's memcpy unpadding at :1062-1107 has
  no equivalent because our video frames are dense arrays);
- frame accumulation uses a simple list instead of GstAdapter.

Converter *subplugins* (flatbuf/protobuf/… of §2.6) register via
:mod:`nnstreamer_tpu.converters`.
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional

import numpy as np

from ..pipeline.caps import Caps, Structure
from ..pipeline.element import Element, FlowReturn
from ..pipeline.registry import register_element
from ..tensor.buffer import TensorBuffer, frames_to_ns, is_device_array
from ..tensor.caps_util import caps_from_config, flexible_tensors_caps
from ..tensor.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensor.meta import TensorMetaInfo
from ..tensor.types import TensorType, dim_parse, np_shape_to_dim
from .src import _CHANNELS, video_template_caps

_AUDIO_TYPES = {"S8": TensorType.INT8, "U8": TensorType.UINT8,
                "S16LE": TensorType.INT16, "U16LE": TensorType.UINT16,
                "S32LE": TensorType.INT32, "U32LE": TensorType.UINT32,
                "F32LE": TensorType.FLOAT32, "F64LE": TensorType.FLOAT64}


class _Adapter:
    """Byte-FIFO accumulate/split across buffer boundaries — the GstAdapter
    role in the reference's chunk/merge path (gsttensor_converter.c:783,
    1110-1154): incoming buffers of ARBITRARY size are re-chunked into
    exact frame multiples, with the remainder carried to the next buffer."""

    def __init__(self) -> None:
        self._chunks: List[np.ndarray] = []   # 1-D uint8 views
        self.available = 0

    def push(self, raw: np.ndarray) -> None:
        if raw.nbytes:
            self._chunks.append(raw)
            self.available += raw.nbytes

    def take(self, n: int) -> np.ndarray:
        assert n <= self.available
        from ..pipeline.tracing import record_copy

        record_copy(n)   # re-chunking is a real copy: keep it observable
        out = np.empty(n, np.uint8)
        filled = 0
        while filled < n:
            c = self._chunks[0]
            m = min(n - filled, c.nbytes)
            out[filled:filled + m] = c[:m]
            if m == c.nbytes:
                self._chunks.pop(0)
            else:
                self._chunks[0] = c[m:]
            filled += m
        self.available -= n
        return out

    def compact(self) -> None:
        """Own the carried remainder: pushed chunks are zero-copy VIEWS of
        producer arrays, valid only within the chain call that pushed them —
        a producer reusing its scratch buffer would otherwise corrupt bytes
        still queued here.  Call at the end of each chain call."""
        if not self._chunks:
            return
        from ..pipeline.tracing import record_copy

        record_copy(self.available)
        if len(self._chunks) == 1:
            self._chunks[0] = self._chunks[0].copy()
        else:
            self._chunks = [np.concatenate(self._chunks)]

    def clear(self) -> None:
        self._chunks.clear()
        self.available = 0


@register_element
class TensorConverter(Element):
    FACTORY = "tensor_converter"
    PROPERTIES = {
        "frames-per-tensor": (1, "frames batched into one tensor"),
        "input-dim": (None, "forced dim for octet streams"),
        "input-type": (None, "forced type for octet streams"),
        "set-timestamp": (True, "synthesize PTS when absent"),
        "mode": (None, "custom converter subplugin: 'custom-code:<name>'"),
        "sub-plugins": (None, "reference READABLE property: registered "
                              "converter subplugins (get_property "
                              "returns the live list)"),
    }

    #: reference G_PARAM_READABLE-only (enforced by Element.set_property)
    READONLY_PROPERTIES = ("sub-plugins",)

    def get_property(self, key):
        if key in ("sub-plugins", "sub_plugins"):
            from ..converters import list_converters

            return ",".join(list_converters())   # registry is sorted
        return super().get_property(key)

    def _make_pads(self):
        sink_tmpl = (video_template_caps()
                     .append(Caps([Structure("audio/x-raw", {})]))
                     .append(Caps([Structure("text/x-raw", {})]))
                     .append(Caps([Structure("application/octet-stream", {})]))
                     .append(flexible_tensors_caps()))
        self.add_sink_pad(sink_tmpl, "sink")
        from ..tensor.caps_util import tensors_template_caps

        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        self._pending: List[np.ndarray] = []
        self._pending_pts: Optional[int] = None
        self._out_config: Optional[TensorsConfig] = None
        self._media: Optional[str] = None
        self._custom = None
        self._adapter = _Adapter()
        self._base_pts: Optional[int] = None   # PTS of adapter head
        self._emitted_frames = 0               # frames since _base_pts
        mode = self.mode
        if mode:
            kind, _, name = str(mode).partition(":")
            if kind == "custom-script":
                # reference tensor_converter_python3.cc contract: the
                # mode value is a .py file path
                from ..converters.python import PythonScriptConverter

                self._custom = PythonScriptConverter(name)
            else:
                from ..converters import find_converter

                self._custom = find_converter(name)

    # -- negotiation ---------------------------------------------------------
    def set_caps(self, pad, caps):
        st = caps.first()
        self._media = st.name
        fpt = int(self.frames_per_tensor)
        rate = st.get("framerate")
        if isinstance(rate, Fraction) and fpt > 1:
            rate = rate / fpt
        if self._custom is not None:
            cfg = self._custom.get_out_config(caps)
        elif st.name == "video/x-raw":
            w, h = int(st.get("width")), int(st.get("height"))
            fmt = str(st.get("format"))
            ch = _CHANNELS[fmt]
            dims = (ch, w, h) if fpt == 1 else (ch, w, h, fpt)
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(TensorType.UINT8, dims)]),
                rate=rate if isinstance(rate, Fraction) else Fraction(30, 1))
        elif st.name == "audio/x-raw":
            fmt = str(st.get("format", "S16LE"))
            dtype = _AUDIO_TYPES.get(fmt)
            if dtype is None:
                raise ValueError(f"unsupported audio format {fmt}")
            ch = int(st.get("channels", 1))
            srate = st.get("rate")
            self._audio_dtype = dtype
            self._audio_channels = ch
            self._audio_srate = int(srate) if srate else 0
            if fpt > 1:
                # explicit frames-per-tensor: announce NOW, adapter
                # re-chunks arbitrary incoming buffer sizes (reference
                # gsttensor_converter.c:1110-1113 frames_in = buf/frame +
                # adapter accumulate/split at :783)
                out_rate = (Fraction(self._audio_srate, fpt)
                            if self._audio_srate else Fraction(0, 1))
                cfg = TensorsConfig(
                    info=TensorsInfo([TensorInfo(dtype, (ch, fpt))]),
                    rate=out_rate)
                self._announce(cfg)
                return
            # fpt=1: frames-per-buffer fixed by the FIRST buffer's sample
            # count; later buffers of different size are re-chunked by the
            # adapter to that negotiated count
            self._out_config = None
            return  # announce on first buffer
        elif st.name == "text/x-raw":
            dim = dim_parse(str(self.input_dim)) if self.input_dim else (256,)
            self._text_frame_dims = dim
            if fpt > 1:
                dim = dim + (fpt,)
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(TensorType.UINT8, dim)]),
                rate=rate if isinstance(rate, Fraction) else Fraction(0, 1))
        elif st.name == "application/octet-stream":
            if not self.input_dim or not self.input_type:
                raise ValueError(
                    "octet stream requires input-dim and input-type")
            dim = dim_parse(str(self.input_dim))
            if fpt > 1:
                dim = dim + (fpt,)
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(
                    TensorType.from_string(str(self.input_type)), dim)]),
                rate=rate if isinstance(rate, Fraction) else Fraction(0, 1))
        elif st.name == "other/tensors":  # flexible → static promotion
            self._out_config = None
            return  # per-buffer meta decides; announced on first buffer
        else:
            raise ValueError(f"unsupported media type {st.name}")
        self._announce(cfg)

    def _announce(self, cfg: TensorsConfig) -> None:
        self._out_config = cfg
        self.announce_src_caps(caps_from_config(cfg))

    # -- dataflow ------------------------------------------------------------
    def chain(self, pad, buf: TensorBuffer) -> FlowReturn:
        if self._custom is not None:
            out = self._custom.convert(buf)
            return self.push(out)
        media = self._media
        if media == "video/x-raw":
            return self._chain_video(buf)
        if media == "audio/x-raw":
            return self._chain_audio(buf)
        if media == "text/x-raw":
            return self._chain_text(buf)
        if media == "application/octet-stream":
            return self._chain_octet(buf)
        if media == "other/tensors":
            return self._chain_flex(buf)
        raise RuntimeError(f"no caps negotiated on {self.name}")

    def plan_step(self):
        # fused dispatch covers the stateless 1:1 conversions; the
        # accumulating paths (frames-per-tensor>1, audio/text adapters,
        # flex promotion) keep interpreted dispatch
        if self._custom is not None and hasattr(self._custom, "convert"):
            return self._custom.convert
        if self._media == "video/x-raw" \
                and int(self.frames_per_tensor) == 1:
            return self._video_frame
        return None

    def lower_reason(self):
        if self.mode:
            return "custom converter subplugins run host code"
        if int(self.frames_per_tensor) != 1:
            return "frames-per-tensor>1 accumulates state across buffers"
        media = getattr(self, "_media", None)
        if media not in (None, "video/x-raw"):
            return (f"converting {media} re-chunks through the host "
                    "adapter")
        return None

    def lower_step(self):
        # only the video fpt=1 path is a pure payload passthrough; the
        # pre-negotiation state (media unknown) also opts out — plans
        # compile on the first buffer, after caps
        if self.lower_reason() is not None \
                or getattr(self, "_media", None) != "video/x-raw":
            return None
        from ..pipeline.element import LoweredStep

        return LoweredStep(lambda params, ts: [ts[0]])

    def _video_frame(self, buf: TensorBuffer) -> TensorBuffer:
        t = buf.tensors[0]
        return buf.with_tensors(
            [t if is_device_array(t) else buf.np(0)])

    def _chain_video(self, buf: TensorBuffer) -> FlowReturn:
        fpt = int(self.frames_per_tensor)
        # (h,w,c) video IS the tensor layout: pass the payload handle
        # through untouched -- a device-resident frame (HBM handle from
        # ``videotestsrc device-cache``) must NOT be synced to host here,
        # that's the whole point of the device path
        if fpt == 1:
            return self.push(self._video_frame(buf))
        frame = buf.tensors[0] if is_device_array(buf.tensors[0]) \
            else buf.np(0)
        # accumulate frames → one tensor of dims (c,w,h,fpt); device
        # payloads accumulate as handles and stack ON DEVICE, keeping the
        # zero-h2d property of the device path for frames-per-tensor > 1
        self._pending.append(frame)
        if self._pending_pts is None:
            self._pending_pts = buf.pts
        if len(self._pending) < fpt:
            return FlowReturn.OK
        if all(is_device_array(f) for f in self._pending):
            import jax.numpy as jnp

            stacked = jnp.stack(self._pending, axis=0)  # (fpt,h,w,c)
        else:
            stacked = np.stack([np.asarray(f) for f in self._pending],
                               axis=0)  # (fpt,h,w,c)
        self._pending = []
        out = TensorBuffer(tensors=[stacked], pts=self._pending_pts,
                           duration=(buf.duration or 0) * fpt)
        self._pending_pts = None
        return self.push(out)

    def _rebase_pts(self, buf: TensorBuffer) -> None:
        """Re-anchor the synthesized-PTS timeline on an upstream timestamp
        when the adapter is at a frame boundary and the buffer carries a
        valid PTS; a PTS-less buffer continues the running timeline
        (reference _gst_tensor_converter_chain_timestamp :783)."""
        if self._adapter.available == 0 and buf.pts is not None:
            self._base_pts = buf.pts
            self._emitted_frames = 0
        elif self._base_pts is None:
            self._base_pts = 0

    def _chain_audio(self, buf: TensorBuffer) -> FlowReturn:
        ch = self._audio_channels
        samples = np.asarray(buf.np(0))
        if samples.ndim == 1:
            samples = samples.reshape(-1, ch)
        if self._out_config is None:
            # fpt=1: the FIRST buffer's sample count fixes frames/tensor
            n = samples.shape[0]
            out_rate = (Fraction(self._audio_srate, n)
                        if self._audio_srate and n else Fraction(0, 1))
            cfg = TensorsConfig(
                info=TensorsInfo([TensorInfo(self._audio_dtype,
                                             np_shape_to_dim(samples.shape))]),
                rate=out_rate)
            self._announce(cfg)
        info = self._out_config.info[0]
        frames_out = info.np_shape[0]
        out_bytes = info.size
        srate = self._audio_srate
        self._rebase_pts(buf)

        def stamp(fallback_pts, fallback_dur):
            if srate and self.set_timestamp:
                pts = self._base_pts + frames_to_ns(
                    self._emitted_frames, srate, 1)
                dur = frames_to_ns(frames_out, srate, 1)
            else:
                pts, dur = fallback_pts, fallback_dur
            self._emitted_frames += frames_out
            return pts, dur

        # fast path: adapter empty and the buffer is exactly one tensor —
        # zero-copy, but it still advances the synthesized timeline so a
        # later adapter-path buffer continues instead of restarting at base
        if (self._adapter.available == 0
                and samples.shape == info.np_shape):
            pts, dur = stamp(buf.pts, buf.duration)
            out = buf.with_tensors([samples])
            out.pts, out.duration = pts, dur
            return self.push(out)
        self._adapter.push(
            np.ascontiguousarray(samples).reshape(-1).view(np.uint8))
        ret = FlowReturn.OK
        while self._adapter.available >= out_bytes:
            arr = (self._adapter.take(out_bytes)
                   .view(info.np_dtype).reshape(info.np_shape))
            pts, dur = stamp(buf.pts, buf.duration)
            ret = self.push(TensorBuffer(tensors=[arr], pts=pts,
                                         duration=dur,
                                         extra=dict(buf.extra)))
            if ret is FlowReturn.ERROR:
                return ret
        self._adapter.compact()
        return ret

    def _chain_text(self, buf: TensorBuffer) -> FlowReturn:
        """Each text buffer is ONE frame, padded/clipped to the frame size
        (reference :1114-1143); frames-per-tensor>1 stacks N frames."""
        frame_dims = self._text_frame_dims
        frame_size = int(np.prod(frame_dims))
        raw = np.asarray(buf.np(0)).reshape(-1).view(np.uint8)
        if raw.nbytes < frame_size:
            raw = np.concatenate(
                [raw, np.zeros(frame_size - raw.nbytes, np.uint8)])
        frame = raw[:frame_size].reshape(tuple(reversed(frame_dims)))
        fpt = int(self.frames_per_tensor)
        if fpt <= 1:
            return self.push(buf.with_tensors([frame]))
        self._pending.append(frame)
        if self._pending_pts is None:
            self._pending_pts = buf.pts
        if len(self._pending) < fpt:
            return FlowReturn.OK
        stacked = np.stack(self._pending, axis=0)
        self._pending = []
        out = TensorBuffer(tensors=[stacked], pts=self._pending_pts,
                           duration=(buf.duration or 0) * fpt,
                           extra=dict(buf.extra))
        self._pending_pts = None
        return self.push(out)

    def _chain_octet(self, buf: TensorBuffer) -> FlowReturn:
        """Static chunking (reference :1144-1154): arbitrary buffer sizes
        are re-chunked to exact tensor multiples via the adapter — a big
        buffer yields several tensors, small ones accumulate."""
        info = self._out_config.info[0]
        out_bytes = info.size
        self._rebase_pts(buf)
        self._adapter.push(np.asarray(buf.np(0)).reshape(-1).view(np.uint8))
        rate = self._out_config.rate
        ret = FlowReturn.OK
        while self._adapter.available >= out_bytes:
            arr = (self._adapter.take(out_bytes)
                   .view(info.np_dtype).reshape(info.np_shape))
            if rate and self.set_timestamp:
                pts = self._base_pts + frames_to_ns(
                    self._emitted_frames, rate.numerator, rate.denominator)
                dur = frames_to_ns(1, rate.numerator, rate.denominator)
            else:
                pts, dur = buf.pts, buf.duration
            self._emitted_frames += 1
            ret = self.push(TensorBuffer(tensors=[arr], pts=pts,
                                         duration=dur,
                                         extra=dict(buf.extra)))
            if ret is FlowReturn.ERROR:
                return ret
        self._adapter.compact()
        return ret

    def _chain_flex(self, buf: TensorBuffer) -> FlowReturn:
        """Flexible → static promotion: first buffer's meta fixes the config
        (reference :1155-1200)."""
        if self._out_config is None:
            infos = []
            for i in range(buf.num_tensors):
                meta = (buf.metas[i] if buf.metas else
                        TensorMetaInfo.from_info(
                            TensorInfo.from_np(buf.np(i))))
                infos.append(meta.to_info())
            cfg = TensorsConfig(info=TensorsInfo(infos), rate=Fraction(0, 1))
            self._announce(cfg)
        for i, info in enumerate(self._out_config.info):
            got = np_shape_to_dim(buf.np(i).shape)
            if not TensorInfo(info.dtype, got).is_equal(info):
                raise ValueError(
                    f"flexible stream changed shape: {got} != {info.dims}")
        return self.push(buf.with_tensors(
            [buf.np(i) for i in range(buf.num_tensors)]))
