"""Standard element library.  Importing this package registers every element
factory (the reference's plugin registerer role,
gst/nnstreamer/registerer/nnstreamer.c:91-133).
"""

from . import aggregator  # noqa: F401
from . import converter  # noqa: F401
from . import decoder_elem  # noqa: F401
from . import filter_elem  # noqa: F401
from . import mediadec  # noqa: F401
from . import merge_split  # noqa: F401
from . import misc  # noqa: F401
from . import mux  # noqa: F401
from . import rate  # noqa: F401
from . import repo  # noqa: F401
from . import sink  # noqa: F401
from . import sparse  # noqa: F401
from . import src  # noqa: F401
from . import srciio  # noqa: F401
from . import tensor_if  # noqa: F401
from . import trainer  # noqa: F401
from . import transform  # noqa: F401
from ..llm import element as _llm_element  # noqa: F401
from ..query import client as _query_client  # noqa: F401
from ..query import edge as _query_edge  # noqa: F401
from ..query import grpc_service as _query_grpc  # noqa: F401
from ..query import mqtt as _query_mqtt  # noqa: F401
from ..query import server as _query_server  # noqa: F401
from ..query import shm as _query_shm  # noqa: F401

from .aggregator import TensorAggregator
from .converter import TensorConverter
from .decoder_elem import TensorDecoder
from .filter_elem import TensorFilter
from .merge_split import TensorMerge, TensorSplit
from .misc import DataRepoSrc, Join, TensorCrop, TensorDebug
from .mux import TensorDemux, TensorMux
from .rate import TensorRate
from .repo import TensorRepoSink, TensorRepoSrc
from .sink import FakeSink, FileSink, TensorSink
from .sparse import TensorSparseDec, TensorSparseEnc
from .src import AudioTestSrc, VideoTestSrc
from .srciio import TensorSrcIIO
from .tensor_if import TensorIf, register_if_custom
from .trainer import (JaxTrainer, TensorTrainer, TrainerFramework,
                      find_trainer, register_trainer)
from .transform import TensorTransform

__all__ = [
    "TensorConverter", "TensorDecoder", "TensorFilter", "TensorSink",
    "FakeSink", "FileSink", "VideoTestSrc", "AudioTestSrc",
    "TensorTransform", "TensorMux", "TensorDemux", "TensorMerge",
    "TensorSplit", "TensorAggregator", "TensorIf", "register_if_custom",
    "TensorRate", "TensorRepoSink", "TensorRepoSrc", "TensorSparseEnc",
    "TensorSparseDec", "TensorDebug", "Join", "TensorCrop", "DataRepoSrc",
    "TensorTrainer", "JaxTrainer", "TrainerFramework", "find_trainer",
    "register_trainer", "TensorSrcIIO",
]
