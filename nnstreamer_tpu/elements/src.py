"""Media test sources: videotestsrc/audiotestsrc equivalents.

The reference relies on GStreamer's videotestsrc for every golden test and
benchmark pipeline (e.g. tests/nnstreamer_filter_tensorflow2_lite/runTest.sh).
These sources produce the same role: deterministic synthetic frames at a
negotiated format/rate, honoring downstream caps constraints (capsfilter).
"""

from __future__ import annotations

import os
from fractions import Fraction
from typing import Optional

import numpy as np

from ..pipeline.caps import ANY_FRAMERATE, Caps, FractionRange, IntRange, Structure
from ..pipeline.graph import Source
from ..pipeline.registry import register_element
from ..tensor.buffer import SECOND, TensorBuffer

VIDEO_FORMATS = ["RGB", "BGRx", "GRAY8"]  # reference converter's video set
_CHANNELS = {"RGB": 3, "BGRx": 4, "GRAY8": 1}


def video_template_caps() -> Caps:
    return Caps([Structure("video/x-raw", {
        "format": list(VIDEO_FORMATS),
        "width": IntRange(1, 1 << 15),
        "height": IntRange(1, 1 << 15),
        "framerate": ANY_FRAMERATE,
    })])


@register_element
class VideoTestSrc(Source):
    """Deterministic video pattern source.

    Patterns: ``smpte`` (color bands), ``gradient``, ``checkers``,
    ``random`` (seeded), ``solid`` (color via ``foreground-color``).
    """

    FACTORY = "videotestsrc"
    PROPERTIES = {
        "num-buffers": (-1, "frames to emit, -1 = unlimited"),
        "pattern": ("smpte", "smpte|gradient|checkers|random|solid"),
        "foreground-color": (0xFFFFFF, "solid pattern RGB"),
        "seed": (42, "random pattern seed"),
        "cache-frames": (0, "pre-render N distinct frames and cycle them "
                            "(0 = render every frame); removes source "
                            "render cost from throughput measurements"),
        "device-cache": (0, "pre-render N distinct frames, stage them to "
                            "the default jax device ONCE at start, and "
                            "cycle the device-resident handles; downstream "
                            "device consumers (tensor_filter) then see "
                            "zero host->device traffic per frame -- the "
                            "TPU-native source mode (frames live in HBM "
                            "for their whole pipeline life)"),
    }

    def _make_pads(self):
        self.add_src_pad(video_template_caps(), "src")

    def start(self):
        self._count = 0
        self._rng = np.random.default_rng(int(self.seed))
        self._cache: Optional[list] = None

    def negotiate(self) -> Caps:
        allowed = self.src_pad.peer_allowed_caps()
        caps = self.src_pad.template.intersect(allowed)
        if caps.is_empty():
            raise ValueError(f"{self.name}: cannot negotiate with downstream")
        # Default resolution when unconstrained.
        fixed = caps.first().fields
        defaults = {"width": 320, "height": 240,
                    "framerate": Fraction(30, 1)}
        s = dict(fixed)
        for k, d in defaults.items():
            v = s.get(k)
            if isinstance(v, (IntRange, FractionRange)):
                # prefer the default when allowed, else let fixate() pick
                # from the range (its low end)
                if v.contains(d):
                    s[k] = d
            elif v is None:
                s[k] = d
        caps = Caps([Structure("video/x-raw", s)]).fixate()
        self._caps = caps
        st = caps.first()
        self._w, self._h = int(st.get("width")), int(st.get("height"))
        self._format = str(st.get("format"))
        self._rate = st.get("framerate")
        return caps

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.num_buffers)
        if n >= 0 and self._count >= n:
            return None
        kd, k = int(self.device_cache), int(self.cache_frames)
        if kd > 0:
            if self._cache is None:
                # one device_put per distinct frame, ONCE -- after this the
                # source emits existing HBM handles (no per-frame device op,
                # no per-frame host render, no h2d in the steady state;
                # jax arrays are immutable, so no freeze needed)
                import jax

                dev = jax.devices()[0]
                self._cache = [jax.device_put(self._render(i), dev)
                               for i in range(kd)]
            frame = self._cache[self._count % kd]
        elif k > 0:
            if self._cache is None:
                self._cache = []
                for i in range(k):
                    f = self._render(i)
                    # the same object is re-emitted every cycle: freeze it
                    # so an in-place mutation downstream raises instead of
                    # silently corrupting later cycles
                    f.flags.writeable = False
                    self._cache.append(f)
            frame = self._cache[self._count % k]
        else:
            frame = self._render(self._count)
        rate = self._rate or Fraction(30, 1)
        dur = SECOND * rate.denominator // max(rate.numerator, 1)
        buf = TensorBuffer(tensors=[frame], pts=self._count * dur,
                           duration=dur)
        self._count += 1
        return buf

    #: GStreamer videotestsrc numeric pattern ids → nearest pattern
    #: here (ssat lines say pattern=13/15/18; byte-goldens cannot be
    #: verbatim-portable anyway — gst's pixel generators are its own —
    #: but the launch lines must RUN with a deterministic look-alike)
    GST_PATTERN_IDS = {
        0: "smpte", 1: "random", 2: "black", 3: "white", 7: "checkers",
        8: "checkers", 9: "checkers", 10: "checkers", 11: "gradient",
        13: "smpte", 14: "gradient", 15: "gradient", 16: "gradient",
        17: "solid", 18: "checkers", 19: "smpte", 20: "smpte",
        23: "gradient",
    }

    def _render(self, n: int) -> np.ndarray:
        w, h, ch = self._w, self._h, _CHANNELS[self._format]
        pattern = str(self.pattern)
        try:
            pattern = self.GST_PATTERN_IDS.get(int(pattern), "smpte")
        except ValueError:
            pass                      # a name, not a numeric gst id
        if pattern in ("black", "white"):
            px = np.full((h, w, ch), 0 if pattern == "black" else 255,
                         dtype=np.uint8)
            if ch == 4:
                px[..., 3] = 255
            return px
        if pattern == "random":
            return self._rng.integers(0, 256, (h, w, ch), dtype=np.uint8)
        if pattern == "solid":
            color = int(self.foreground_color)
            rgb = [(color >> 16) & 0xFF, (color >> 8) & 0xFF, color & 0xFF]
            px = np.array((rgb + [255])[:ch], dtype=np.uint8)
            return np.broadcast_to(px, (h, w, ch)).copy()
        if pattern == "checkers":
            yy, xx = np.mgrid[0:h, 0:w]
            cell = ((xx // 8 + yy // 8 + n) % 2) * 255
            return np.repeat(cell.astype(np.uint8)[..., None], ch, axis=2)
        if pattern == "gradient":
            row = np.linspace(0, 255, w, dtype=np.uint8)
            frame = np.broadcast_to(row[None, :, None], (h, w, ch))
            return np.ascontiguousarray(
                np.roll(frame, shift=n, axis=1))
        # smpte-ish: 7 vertical color bars
        bars = np.array([
            [191, 191, 191], [191, 191, 0], [0, 191, 191], [0, 191, 0],
            [191, 0, 191], [191, 0, 0], [0, 0, 191]], dtype=np.uint8)
        idx = (np.arange(w) * 7 // max(w, 1)).clip(0, 6)
        frame = bars[idx][None, :, :].repeat(h, axis=0)
        if ch == 1:
            frame = frame.mean(axis=2, keepdims=True).astype(np.uint8)
        elif ch == 4:
            frame = np.concatenate(
                [frame, np.full((h, w, 1), 255, np.uint8)], axis=2)
        return np.ascontiguousarray(frame)


@register_element
class AudioTestSrc(Source):
    """Sine-wave audio source (audiotestsrc role)."""

    FACTORY = "audiotestsrc"
    PROPERTIES = {
        "num-buffers": (-1, ""),
        "samplesperbuffer": (1024, ""),
        "freq": (440.0, "sine frequency"),
    }

    def _make_pads(self):
        self.add_src_pad(Caps([Structure("audio/x-raw", {
            "format": ["S16LE", "U8", "F32LE"],
            "channels": IntRange(1, 16),
            "rate": IntRange(1, 384000),
        })]), "src")

    def start(self):
        self._count = 0

    def negotiate(self) -> Caps:
        allowed = self.src_pad.peer_allowed_caps()
        caps = self.src_pad.template.intersect(allowed)
        s = dict(caps.first().fields)
        if not isinstance(s.get("channels"), int):
            s["channels"] = 1
        if not isinstance(s.get("rate"), int):
            s["rate"] = 44100
        caps = Caps([Structure("audio/x-raw", s)]).fixate()
        self._caps = caps
        st = caps.first()
        self._format = str(st.get("format"))
        self._channels = int(st.get("channels"))
        self._rate = int(st.get("rate"))
        return caps

    def create(self) -> Optional[TensorBuffer]:
        n = int(self.num_buffers)
        if n >= 0 and self._count >= n:
            return None
        spb = int(self.samplesperbuffer)
        t0 = self._count * spb
        t = (np.arange(spb) + t0) / self._rate
        wave = np.sin(2 * np.pi * float(self.freq) * t)
        if self._format == "S16LE":
            data = (wave * 32767).astype(np.int16)
        elif self._format == "U8":
            data = ((wave * 127) + 128).astype(np.uint8)
        else:
            data = wave.astype(np.float32)
        samples = np.repeat(data[:, None], self._channels, axis=1)
        pts = t0 * SECOND // self._rate
        dur = spb * SECOND // self._rate
        buf = TensorBuffer(tensors=[samples], pts=pts, duration=dur)
        self._count += 1
        return buf


@register_element
class FileSrc(Source):
    """Reads a file and pushes its bytes downstream (GStreamer filesrc
    role).  The reference's ssat pipelines open nearly every golden input
    this way (e.g. tests/nnstreamer_filter_caffe2/runTest.sh:
    ``filesrc location=data/5 blocksize=-1 ! application/octet-stream ! …``).

    Caps are whatever downstream will accept (a caps string right after the
    element types the bytes, exactly like the reference pipelines);
    ``blocksize=-1`` pushes the whole file as ONE buffer, otherwise the
    file streams in ``blocksize``-byte chunks (GstBaseSrc default 4096).
    """

    FACTORY = "filesrc"
    PROPERTIES = {
        "location": (None, "path of the file to read"),
        "blocksize": (4096, "bytes per buffer; -1 = whole file at once"),
    }

    def _make_pads(self):
        self.add_src_pad(Caps.any(), "src")

    def start(self):
        if not self.location:
            raise ValueError(f"{self.name}: location required")
        path = str(self.location)
        if not os.path.isfile(path):
            raise FileNotFoundError(f"{self.name}: no such file: {path}")
        self._f = open(path, "rb")

    def stop(self):
        f = getattr(self, "_f", None)
        if f is not None and not f.closed:
            f.close()

    def negotiate(self) -> Caps:
        return _negotiate_byte_caps(self)

    def create(self) -> Optional[TensorBuffer]:
        size = int(self.blocksize)
        chunk = self._f.read() if size < 0 else self._f.read(size)
        if not chunk:
            return None
        # no pts: file bytes carry no timeline (GStreamer filesrc leaves
        # timestamps unset too — stamping 0 would make QoS throttling and
        # tensor_rate collapse all chunks onto one instant)
        return TensorBuffer(tensors=[np.frombuffer(chunk, np.uint8)])


def _negotiate_byte_caps(el) -> Caps:
    """Byte-source negotiation shared by filesrc/multifilesrc: take
    downstream's constraint, defaulting to raw octets when downstream
    is unconstrained (e.g. fakesink)."""
    allowed = el.src_pad.peer_allowed_caps()
    if allowed.is_empty():
        raise ValueError(f"{el.name}: cannot negotiate with downstream")
    if allowed.is_any():
        return Caps([Structure("application/octet-stream", {})])
    return allowed.fixate()


def _indexed_path(location, index: int, name: str) -> str:
    """printf-style ``location % index`` (GStreamer multifile pattern,
    e.g. ``out_%1d.log`` / ``frames.%d``) with a named error for a
    pattern that doesn't consume the index."""
    try:
        return str(location) % index
    except (TypeError, ValueError) as exc:
        # %-formatting raises TypeError whenever the index is not
        # consumed, so this covers patterns with no directive too
        raise ValueError(f"{name}: location {location!r} must contain "
                         f"one %d-style index directive ({exc})") from exc


@register_element
class MultiFileSrc(Source):
    """Reads an INDEXED file sequence (GStreamer multifilesrc role —
    the ssat detection pipelines stream golden tensors this way:
    ``multifilesrc location=x.%d start-index=0 stop-index=9
    caps=application/octet-stream``).  Each file is pushed as one
    buffer; the sequence ends at stop-index, or at the first missing
    file when stop-index is -1."""

    FACTORY = "multifilesrc"
    PROPERTIES = {
        "location": (None, "printf pattern, e.g. frames.%d"),
        "start-index": (0, "first index"),
        "stop-index": (-1, "last index; -1 = until a file is missing"),
        "caps": (None, "caps of the byte stream (else negotiated like "
                       "filesrc)"),
        "loop": (False, "restart from start-index at the end"),
    }

    def _make_pads(self):
        self.add_src_pad(Caps.any(), "src")

    def start(self):
        if not self.location:
            raise ValueError(f"{self.name}: location required")
        self._idx = int(self.start_index)
        stop = int(self.stop_index)
        if stop >= 0 and self._idx > stop:
            raise ValueError(f"{self.name}: start-index {self._idx} > "
                             f"stop-index {stop}")
        # the pattern must be well-formed even if the first file is
        # checked lazily (stop-index=-1 ends at the first gap)
        _indexed_path(self.location, self._idx, self.name)

    def negotiate(self) -> Caps:
        if self.caps:
            c = self.caps
            caps = Caps.from_string(c) if isinstance(c, str) else c
            return caps.fixate()
        return _negotiate_byte_caps(self)

    def create(self) -> Optional[TensorBuffer]:
        stop = int(self.stop_index)
        while True:
            if stop >= 0 and self._idx > stop:
                if not bool(self.loop):
                    return None
                self._idx = int(self.start_index)
            path = _indexed_path(self.location, self._idx, self.name)
            if not os.path.isfile(path):
                if stop >= 0:
                    raise FileNotFoundError(
                        f"{self.name}: no such file: {path} (index "
                        f"{self._idx} <= stop-index {stop})")
                if bool(self.loop) and self._idx != int(self.start_index):
                    self._idx = int(self.start_index)
                    continue
                return None
            with open(path, "rb") as fh:
                chunk = fh.read()
            self._idx += 1
            return TensorBuffer(tensors=[np.frombuffer(chunk, np.uint8)])
