"""tensor_rate: framerate control + throttling for tensor streams.

Parity with gst/nnstreamer/elements/gsttensor_rate.c: drop/duplicate frames
to hit a target ``framerate``; ``throttle`` mode simply drops to an upper
bound (the QoS role the reference wires to tensor_filter throttling).
"""

from __future__ import annotations

from fractions import Fraction

from ..pipeline.element import Element, FlowReturn, QoSEvent
from ..pipeline.registry import register_element
from ..tensor.buffer import SECOND
from ..tensor.caps_util import caps_from_config, config_from_caps, \
    tensors_template_caps


@register_element
class TensorRate(Element):
    FACTORY = "tensor_rate"
    PROPERTIES = {
        "framerate": (None, "target rate 'N/D'"),
        "throttle": (True, "drop-only (no duplication)"),
    }

    def _make_pads(self):
        self.add_sink_pad(tensors_template_caps(), "sink")
        self.add_src_pad(tensors_template_caps(), "src")

    def start(self):
        if self.framerate in (None, ""):
            raise ValueError(f"{self.name}: framerate required")
        self._target = Fraction(str(self.framerate))
        self._qos_proportion = 1.0     # downstream slowdown (QoS feedback)
        self._next_pts = 0
        self.dropped = 0
        self.duplicated = 0

    def on_upstream_event(self, pad, event):
        """Close the QoS loop: a downstream slowdown report lowers the
        EFFECTIVE output rate (open-loop target ÷ proportion); a catch-up
        report (jitter <= 0) restores the configured rate.  The event still
        propagates upstream so producers can throttle too."""
        if isinstance(event, QoSEvent):
            self._qos_proportion = (1.0 if event.jitter_ns <= 0
                                    else max(1.0, event.proportion))
            super().on_upstream_event(pad, event)
            return True
        return super().on_upstream_event(pad, event)

    @property
    def effective_rate(self) -> Fraction:
        """QoS-adapted output rate: target / proportion.  The proportion
        is quantized to millesimals for an exact Fraction — reports in
        (1.0, 1.001) round DOWN to no-op, which is below any actionable
        slowdown (the reference's integer-ns throttling interval
        quantizes harder)."""
        p = self._qos_proportion
        quant = Fraction(int(p * 1000), 1000)
        return self._target if p <= 1.0 or quant <= 1 \
            else self._target / quant

    def set_caps(self, pad, caps):
        cfg = config_from_caps(caps)
        cfg.rate = self._target
        self.announce_src_caps(caps_from_config(cfg))

    def chain(self, pad, buf):
        eff = self.effective_rate
        interval = SECOND * eff.denominator // eff.numerator
        pts = buf.pts or 0
        if pts + (buf.duration or 0) < self._next_pts:
            self.dropped += 1
            return FlowReturn.DROPPED
        ret = FlowReturn.OK
        while pts + (buf.duration or interval) >= self._next_pts:
            out = buf.copy()
            out.pts = self._next_pts
            out.duration = interval
            ret = self.push(out)
            self._next_pts += interval
            if bool(self.throttle):
                break
            if ret is not FlowReturn.OK:
                break
        return ret
