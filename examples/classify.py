"""Image classification pipeline (BASELINE config 1).

videotestsrc → tensor_converter → tensor_filter (MobileNetV2, batch=8) →
image_labeling → tensor_sink.  When the reference checkout is present the
real ImageNet weights are imported from its quant tflite on first run.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from nnstreamer_tpu import parse_launch  # noqa: E402

REF = "/root/reference/tests/test_models"
CKPT = "/tmp/nns_tpu_mobilenet_ckpt"


def checkpoint_props() -> str:
    """Import real weights once, if the reference artifacts exist."""
    tfl = os.path.join(REF, "models", "mobilenet_v2_1.0_224_quant.tflite")
    if not os.path.isfile(tfl):
        return ""
    if not os.path.isdir(CKPT):
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        from tflite_weights import import_weights

        import_weights("mobilenet_v2", tfl, CKPT)
    return f",checkpoint:{CKPT},dtype:float32"


def main() -> None:
    # any registry classifier works here; `vit` swaps in the
    # attention-family model (Pallas flash encoder on TPU)
    model = sys.argv[1] if len(sys.argv) > 1 else "mobilenet_v2"
    props = checkpoint_props() if model == "mobilenet_v2" else ""
    labels = os.path.join(REF, "labels", "labels.txt")
    label_opt = f"option1={labels}" if os.path.isfile(labels) else ""
    p = parse_launch(
        "videotestsrc num-buffers=32 pattern=gradient ! "
        "video/x-raw,format=RGB,width=224,height=224,framerate=30/1 ! "
        "tensor_converter ! "
        f"tensor_filter framework=xla model={model} "
        f"custom=seed:0{props} batch=8 ! "
        "queue ! "
        f"tensor_decoder mode=image_labeling {label_opt} ! "
        "tensor_sink name=out")
    p.get("out").connect(
        "new-data",
        lambda b: print(f"pts={b.pts}  class={b.extra['index']}"
                        f"  label={b.extra.get('label')}"))
    p.run(timeout=600)


if __name__ == "__main__":
    main()
