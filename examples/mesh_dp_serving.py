"""Multi-chip data-parallel serving from the stream (custom=mesh:dp=N).

The reference's among-device story offloads whole sub-pipelines to
other devices over TCP (tensor_query_client.c:656-743).  The TPU-native
superset needs no second pipeline: `tensor_filter custom=mesh:dp=N`
makes the ONE batched serving executable span an N-device ("dp",)
jax mesh — params replicated, the stream micro-batch split along axis 0
by XLA's partitioner.  This example runs the same frames through the
single-device and the dp=4-sharded pipelines and checks the outputs are
identical (they are the SAME executable semantics, just placed wider).

Run (virtual 4-device CPU mesh — the same strategy the test suite and
the driver's dryrun use for multi-chip validation without hardware):

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python examples/mesh_dp_serving.py

On a real multi-chip TPU host the same launch line shards over real
chips; collectives ride ICI.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from nnstreamer_tpu import parse_launch  # noqa: E402

N_FRAMES = 24
BATCH = 8


def run(mesh_prop: str):
    labels = []
    p = parse_launch(
        f"videotestsrc num-buffers={N_FRAMES} pattern=random cache-frames=8 ! "
        "video/x-raw,format=RGB,width=224,height=224,framerate=30/1 ! "
        "tensor_converter ! "
        "tensor_filter framework=xla model=mobilenet_v2 "
        f"custom=seed:0{mesh_prop} batch={BATCH} name=f ! "
        "tensor_decoder mode=image_labeling ! tensor_sink name=out")
    p.get("out").connect("new-data",
                         lambda b: labels.append(b.extra.get("index")))
    p.run(timeout=300)
    return labels


def main() -> int:
    import jax

    n_dev = len(jax.devices())
    # largest dp <= 4 that divides BATCH (the element requires an even
    # split; a 3-device host clamps to dp=2)
    dp = next((d for d in (4, 2) if d <= n_dev and BATCH % d == 0), 1)
    if dp < 2:
        print(f"need >=2 devices for a dp mesh, have {n_dev} — "
              "set XLA_FLAGS=--xla_force_host_platform_device_count=4")
        return 1

    single = run("")
    sharded = run(f",mesh:dp={dp}")
    assert len(single) == len(sharded) == N_FRAMES, (
        f"{len(single)} vs {len(sharded)} of {N_FRAMES}")
    assert single == sharded, "sharded serving diverged from single-device"
    uniq = sorted(set(single))
    print(f"OK: {N_FRAMES} frames, dp={dp} sharded == single-device "
          f"(labels seen: {uniq})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
