"""On-device training from a file dataset (reference tensor_trainer +
datareposrc pattern, gstdatareposrc.c:15-21).

A synthetic dataset file streams through the native prefetch reader into
tensor_trainer, which runs a jitted Adam step per batch and writes a
checkpoint at EOS.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from nnstreamer_tpu import parse_launch  # noqa: E402


def make_dataset(path: str, n: int = 64) -> None:
    """Frames of (8 features, 4 one-hot labels) — linearly separable."""
    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 4)).astype(np.float32)
    rows = []
    for _ in range(n):
        x = rng.standard_normal(8).astype(np.float32)
        y = np.zeros(4, np.float32)
        y[int((x @ w).argmax())] = 1.0
        rows.append(x.tobytes() + y.tobytes())
    with open(path, "wb") as f:
        f.write(b"".join(rows))


def main() -> None:
    with tempfile.NamedTemporaryFile(suffix=".dat", delete=False) as f:
        data = f.name
    make_dataset(data)
    ckpt = os.path.join(tempfile.mkdtemp(), "model")
    p = parse_launch(
        f"datareposrc location={data} input-dim=8,4 "
        "input-type=float32,float32 epochs=2 ! "
        f"tensor_trainer name=tr num-inputs=1 num-labels=1 batch-size=8 "
        f"lr=0.01 model-save-path={ckpt} ! "
        "tensor_sink name=out")
    p.run(timeout=600)
    tr = p.get("tr")
    print("summary:", tr.summary)
    print("loss first→last:",
          f"{tr.trainer.losses[0]:.4f} → {tr.trainer.losses[-1]:.4f}")
    print("checkpoint:", ckpt, os.path.isdir(ckpt) or os.path.exists(ckpt))
    os.unlink(data)


if __name__ == "__main__":
    main()
