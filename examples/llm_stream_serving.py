"""Token-streaming LLM serving: continuous batching over the query wire.

One launch string serves N concurrent token streams from a single
device loop (``nnstreamer_tpu/llm``): ``tensor_query_serversrc``
admits prompt requests (QoS + queue-depth admission unchanged),
``tensor_llm`` holds one KV-cache slot per live stream and advances
EVERY resident sequence per padded device step (vLLM-style continuous
batching — sequences join after their flash-path prefill, leave on
stop-token/max-new/disconnect), and ``tensor_query_serversink``
streams the per-token ``[1, 1]`` reply frames back in exact per-client
order.

No reference analogue — this is the stateful serving tier the
request/response plane grew into.  Run with ``--trace`` flags via
launch.py for the merged prefill/decode timeline.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.llm.client import TokenStreamClient  # noqa: E402
from nnstreamer_tpu.query.server import shutdown_server  # noqa: E402

SID = 71
REQ_CAP = 96
CUSTOM = ("vocab:512,dim:256,heads:8,head_dim:32,mlp:1024,layers:4,"
          "max_seq:512,dtype:float32")


def main() -> None:
    p = parse_launch(
        f"tensor_query_serversrc name=qsrc id={SID} port=0 "
        f"caps=other/tensors,format=static,num_tensors=1,"
        f"dimensions={REQ_CAP},types=int32,framerate=0/1 ! "
        f"tensor_llm name=llm custom={CUSTOM} slots=8 batch=4 "
        f"id={SID} ! "
        f"tensor_query_serversink id={SID}")
    p.play()
    port = p.get("qsrc").bound_port
    print(f"serving on 127.0.0.1:{port}")

    results = {}

    def run(i: int) -> None:
        cli = TokenStreamClient("127.0.0.1", port, timeout=60.0)
        cli.connect()
        try:
            rng = np.random.default_rng(i)
            prompt = rng.integers(0, 512, 6 + 4 * i).astype(np.int32)
            t0 = time.monotonic()
            toks = cli.generate(prompt, max_new=24 + 8 * i,
                                frame_len=REQ_CAP)
            results[i] = (toks, time.monotonic() - t0)
        finally:
            cli.close()

    threads = [threading.Thread(target=run, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, (toks, dt) in sorted(results.items()):
        print(f"client {i}: {len(toks)} tokens in {dt:.2f}s "
              f"({len(toks) / dt:.1f} tok/s) head={toks[:6]}")
    report = p.get("llm").engine.report()
    print(f"engine: mean fill {report['mean_fill']}, "
          f"{report['tokens']} tokens, phases "
          f"{report['phases']['states_pct']}")
    p.stop()
    shutdown_server(SID)


if __name__ == "__main__":
    main()
