"""Among-device offload: a client pipeline sends frames to a server
pipeline that runs inference and answers (BASELINE config 5 pattern;
reference tensor_query_client/server over localhost, the two-process
strategy of tests/nnstreamer_edge/query/runTest.sh).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.query.server import shutdown_server  # noqa: E402

SERVER_ID = 7
CAPS = ("other/tensors,format=static,num_tensors=1,dimensions=3:224:224:1,"
        "types=uint8,framerate=30/1")


def main() -> None:
    # the serving pipeline: frames arrive from remote clients, run through
    # the model, answers route back by client id
    srv = parse_launch(
        f"tensor_query_serversrc name=qsrc id={SERVER_ID} port=0 "
        f"caps={CAPS} ! "
        "tensor_filter framework=xla model=mobilenet_v2 custom=seed:0 ! "
        f"tensor_query_serversink id={SERVER_ID}")
    srv.play()
    port = srv.get("qsrc").bound_port

    # the client pipeline: offloads inference to the server
    cli = parse_launch(
        "videotestsrc num-buffers=8 pattern=checkers ! "
        "video/x-raw,format=RGB,width=224,height=224,framerate=30/1 ! "
        "tensor_converter ! "
        f"tensor_query_client port={port} timeout=60 ! "
        "tensor_decoder mode=image_labeling ! tensor_sink name=out")
    cli.get("out").connect(
        "new-data", lambda b: print(f"pts={b.pts} class={b.extra['index']}"))
    cli.run(timeout=600)
    srv.stop()
    shutdown_server(SERVER_ID)


if __name__ == "__main__":
    main()
