"""LM serving two ways: a token-stream pipeline and the generate() API.

1. Pipeline: appsrc pushes token windows through ``tensor_filter
   framework=xla model=streamformer_lm`` (full-sequence next-token
   logits, the Pallas flash-attention prefill path on TPU); the sink
   callback reads the last position's argmax as the next token.
2. API: KV-cache incremental decoding — the whole prompt prefill +
   continuation runs as ONE compiled ``lax.scan`` (models/streamformer_lm
   ``generate``), so repeat calls skip XLA entirely.

No reference analogue (the reference has no LM path) — this is the
net-new long-context serving axis.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.tensor.buffer import TensorBuffer  # noqa: E402

SEQ = 64


def pipeline_logits() -> None:
    """Token windows in, per-position logits out; the next token is the
    argmax at the LAST position of each window."""
    p = parse_launch(
        "appsrc caps=other/tensors,format=static,num_tensors=1,"
        f"dimensions={SEQ},types=int32,framerate=0/1 name=in ! "
        f"tensor_filter framework=xla model=streamformer_lm "
        f"custom=seq:{SEQ},vocab:256,seed:0 ! "
        "tensor_sink name=out")
    results = []
    p.get("out").connect(
        "new-data",
        lambda b: results.append(int(np.asarray(b.tensors[0])[-1].argmax())))
    p.play()
    rng = np.random.default_rng(0)
    for _ in range(3):
        window = rng.integers(0, 256, (SEQ,), dtype=np.int32)
        p.get("in").push_buffer(TensorBuffer(tensors=[window]))
    p.get("in").end_of_stream()
    p.wait(timeout=600)
    p.stop()
    print(f"pipeline: next token per window = {results}")


def api_generate() -> None:
    import jax.numpy as jnp

    from nnstreamer_tpu.models.streamformer_lm import generate
    from nnstreamer_tpu.parallel.train_step import (StreamFormerConfig,
                                                    init_params)

    cfg = StreamFormerConfig(vocab=256, dim=128, heads=8, head_dim=16,
                             mlp=512, layers=2, experts=2, max_seq=128,
                             dtype=jnp.bfloat16)
    params = init_params(cfg, 0)
    prompt = np.arange(16, dtype=np.int32)
    t0 = time.monotonic()
    toks = generate(params, cfg, prompt, n_tokens=32)   # compiles
    t1 = time.monotonic()
    toks = generate(params, cfg, prompt, n_tokens=32)   # cached program
    t2 = time.monotonic()
    print(f"generate: {toks[:8]}... "
          f"(compile+run {t1 - t0:.2f}s, cached run {t2 - t1:.3f}s, "
          f"{32 / (t2 - t1):.1f} tok/s)")


if __name__ == "__main__":
    pipeline_logits()
    api_generate()
