"""Tensor streaming between processes over the gRPC TensorService
(reference tensor_src_grpc / tensor_sink_grpc).

This process hosts the receiving service; a child process dials in and
pushes frames via SendTensors.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from nnstreamer_tpu import parse_launch  # noqa: E402

SENDER = r"""
import sys
sys.path.insert(0, %(root)r)
import numpy as np
from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.tensor.buffer import TensorBuffer

caps = ("other/tensors,format=static,num_tensors=1,dimensions=8:4,"
        "types=float32,framerate=30/1")
p = parse_launch(f"appsrc caps={caps} name=in ! "
                 f"tensor_sink_grpc server=false port=%(port)d")
p.play()
for i in range(5):
    p.get("in").push_buffer(
        TensorBuffer(tensors=[np.full((4, 8), float(i), np.float32)]))
p.get("in").end_of_stream()
p.wait(timeout=60)
p.stop()
"""


def main() -> None:
    rx = parse_launch(
        "tensor_src_grpc server=true port=0 num-buffers=5 name=rx ! "
        "tensor_sink name=out")
    rx.get("out").connect(
        "new-data", lambda b: print(f"received {b.np(0).shape} "
                                    f"mean={float(b.np(0).mean()):.1f}"))
    rx.play()
    root = os.path.join(os.path.dirname(__file__), "..")
    code = SENDER % {"root": os.path.abspath(root),
                     "port": rx.get("rx").port}
    proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                          env=dict(os.environ, JAX_PLATFORMS="cpu"))
    rx.wait(timeout=60)
    rx.stop()
    print("sender exit:", proc.returncode)


if __name__ == "__main__":
    main()
