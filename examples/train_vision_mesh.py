"""Data-parallel vision training from the stream (tutorial T6 §3).

A tiny ViT trains over a dp mesh: AppSrc pushes (frames, labels)
batches, tensor_trainer framework=mesh-vision shards each batch over
the mesh's dp axis (params replicated, gradient psum inserted by XLA),
and the checkpoint written at EOS is directly servable by
``tensor_filter framework=xla model=vit custom=checkpoint:...``.

Run on the host with a virtual mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_vision_mesh.py
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from nnstreamer_tpu.elements import TensorTrainer  # noqa: E402
from nnstreamer_tpu.pipeline import AppSrc, Pipeline  # noqa: E402
from nnstreamer_tpu.pipeline.registry import element_factory  # noqa: E402
from nnstreamer_tpu.tensor import TensorBuffer  # noqa: E402


def main() -> None:
    ckpt = os.path.join(tempfile.mkdtemp(), "vit_ckpt")
    p = Pipeline()
    src = AppSrc("src", caps=(
        "other/tensors,format=static,num_tensors=2,"
        "dimensions=3:32:32:8.8,types=uint8.int32,framerate=0/1"))
    trainer = TensorTrainer("tr", framework="mesh-vision", **{
        "num-epochs": 4, "model-save-path": ckpt,
        "custom": ("model:vit,input_size:32,patch:16,dim:32,depth:1,"
                   "heads:2,num_classes:4,dtype:float32,lr:0.01")})
    sink = element_factory("tensor_sink")("out")
    p.add(src, trainer, sink)
    p.link(src, trainer, sink)

    rng = np.random.default_rng(0)
    for i in range(6):
        # learnable toy task: the class is the frame's brightness band
        labels = rng.integers(0, 4, 8).astype(np.int32)
        frames = np.repeat(
            (labels * 64 + 32).astype(np.uint8)[:, None, None, None],
            32 * 32 * 3, axis=1).reshape(8, 32, 32, 3)
        src.push_buffer(TensorBuffer(tensors=[frames, labels], pts=i))
    src.end_of_stream()
    p.run(timeout=600)

    s = trainer.summary
    print(f"trained {s['model']} over mesh {s['mesh']}: "
          f"loss {trainer.trainer.losses[0]:.3f} -> {s['final_loss']:.3f}")
    print(f"checkpoint: {ckpt}")


if __name__ == "__main__":
    main()
