"""Speech-command recognition: wav file → TF graph → label.

The whole audio front-end (DecodeWav host hoist, Hann-window spectrogram,
TF mel-filterbank MFCC) plus the conv net run as ONE XLA executable inside
``tensor_filter framework=tensorflow`` — the reference's
tests/nnstreamer_filter_tensorflow case 3 as a runnable example.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402

REF = "/root/reference/tests/test_models"
LABELS = ["silence", "unknown", "yes", "no", "up", "down",
          "left", "right", "on", "off", "stop", "go"]


def main() -> None:
    model = os.path.join(REF, "models", "conv_actions_frozen.pb")
    wav = os.path.join(REF, "data", "yes.wav")
    if not (os.path.isfile(model) and os.path.isfile(wav)):
        print("reference checkout not present; nothing to run")
        return
    p = parse_launch(
        f"filesrc location={wav} blocksize=-1 ! application/octet-stream ! "
        "tensor_converter input-dim=1:16022 input-type=int16 ! "
        f"tensor_filter framework=tensorflow model={model} "
        "input-dim=1:16022 input-type=int16 "
        "output-dim=12:1 output-type=float32 "
        "custom=inputname:wav_data,outputname:labels_softmax ! "
        "tensor_sink name=out")

    def report(buf):
        sm = np.asarray(buf.tensors[0]).ravel()
        k = int(sm.argmax())
        print(f"heard: {LABELS[k]!r}  (p={sm[k]:.3f})")

    p.get("out").connect("new-data", report)
    p.run(timeout=300)


if __name__ == "__main__":
    main()
