"""Two-stage cascade: detect → crop → classify.

The composite pipeline shape the reference builds from tensor_crop
(gsttensor_crop.c: crop-info from one branch applied to raw tensors
from another): SSD finds boxes on device (the detection tail — prior
decode, threshold, NMS — runs INSIDE the serving executable via the
pushdown, ops/nms.py), the surviving boxes become tensor_crop regions
over the raw frames, and each crop is classified by a second model
through the Single API.

  videotestsrc ─ tee ─ tensor_filter(ssd) ─ bounding_boxes ─ objects ┐
               └───── raw frames ────────────────► tensor_crop ◄─────┘
                                                        │ crops
                                                  FilterSingle(classifier)

Run: JAX_PLATFORMS=cpu python examples/detect_crop_classify.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import tempfile  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.elements import TensorCrop  # noqa: E402
from nnstreamer_tpu.filter.single import FilterSingle  # noqa: E402
from nnstreamer_tpu.models.registry import get_model  # noqa: E402
from nnstreamer_tpu.pipeline import AppSrc, Pipeline  # noqa: E402
from nnstreamer_tpu.pipeline.registry import element_factory  # noqa: E402
from nnstreamer_tpu.tensor import TensorBuffer  # noqa: E402

N_FRAMES = 6
SIZE = 300


def priors_file() -> str:
    """Synthetic box priors (the zoo ships none; same shape as the
    reference's box_priors.txt)."""
    n = get_model("ssd_mobilenet_v2",
                  {"seed": "0"}).out_info[0].np_shape[0]
    rng = np.random.default_rng(0)
    f = tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False)
    for row in (rng.random(n), rng.random(n),
                np.full(n, 0.2), np.full(n, 0.2)):
        f.write(" ".join(f"{v:.6f}" for v in row) + "\n")
    f.close()
    return f.name


def main() -> None:
    frames = []
    detections = []

    # stage 1: one pipeline, tee'd — detection branch + raw-frame branch
    p = parse_launch(
        f"videotestsrc num-buffers={N_FRAMES} pattern=random ! "
        f"video/x-raw,format=RGB,width={SIZE},height={SIZE},"
        "framerate=30/1 ! tensor_converter ! tee name=t "
        "t. ! queue ! tensor_filter framework=xla model=ssd_mobilenet_v2 "
        "custom=seed:0,dtype:float32 name=f ! "
        "tensor_decoder mode=bounding_boxes option1=mobilenet-ssd "
        f"option3={priors_file()} option4={SIZE}:{SIZE} "
        f"option5={SIZE}:{SIZE} ! tensor_sink name=det "
        "t. ! queue ! tensor_sink name=raw")
    p.get("det").connect(
        "new-data", lambda b: detections.append(b.extra["objects"]))
    p.get("raw").connect("new-data", lambda b: frames.append(b.np(0)))
    p.run(timeout=600)
    print(f"stage 1: {len(frames)} frames, "
          f"{sum(len(d) for d in detections)} detections "
          "(ssd tail ran on device)")

    # stage 2: detections -> crop regions -> per-crop classification
    cp = Pipeline()
    raw_src = AppSrc("raw", caps=(
        f"other/tensors,format=static,num_tensors=1,"
        f"dimensions=3:{SIZE}:{SIZE},types=uint8,framerate=0/1"))
    info_src = AppSrc("info", caps=(
        "other/tensors,format=static,num_tensors=1,"
        "dimensions=4:4,types=int32,framerate=0/1"))
    crop = TensorCrop("c")
    sink = element_factory("tensor_sink")("crops")
    cp.add(raw_src, info_src, crop, sink)
    raw_src.src_pad.link(crop.sink_pads[0])
    info_src.src_pad.link(crop.sink_pads[1])
    cp.link(crop, sink)

    classifier = FilterSingle(
        framework="xla", model="mobilenet_v2",
        custom="seed:0,dtype:float32,input_size:64")
    with classifier:
        crops_seen = 0
        for frame, objs in zip(frames, detections):
            regions = []
            for o in objs[:4]:                # top regions per frame
                x = int(np.clip(o.xmin, 0, 1) * (SIZE - 1))
                y = int(np.clip(o.ymin, 0, 1) * (SIZE - 1))
                w = max(8, int((np.clip(o.xmax, 0, 1)
                                - np.clip(o.xmin, 0, 1)) * SIZE))
                h = max(8, int((np.clip(o.ymax, 0, 1)
                                - np.clip(o.ymin, 0, 1)) * SIZE))
                regions.append([x, y, min(w, SIZE - x), min(h, SIZE - y)])
            while len(regions) < 4:           # static region count
                regions.append([0, 0, 8, 8])
            raw_src.push_buffer(TensorBuffer(tensors=[frame]))
            info_src.push_buffer(TensorBuffer(
                tensors=[np.asarray(regions, np.int32)]))
        raw_src.end_of_stream()
        info_src.end_of_stream()
        cp.run(timeout=600)

        for buf in cp.get("crops").results:
            for i in range(buf.num_tensors):
                patch = np.asarray(buf.np(i))
                # classifier expects its input size: nearest resize
                ys = (np.linspace(0, patch.shape[0] - 1, 64)).astype(int)
                xs = (np.linspace(0, patch.shape[1] - 1, 64)).astype(int)
                logits, = classifier.invoke([patch[ys][:, xs]])
                crops_seen += 1
    print(f"stage 2: {crops_seen} crops classified "
          f"(last top-1 class {int(np.argmax(logits))})")


if __name__ == "__main__":
    main()
