"""Recurrent pipeline: state feeds back through the tensor repository.

The reference's repo_rnn topology (tests/nnstreamer_repo_rnn): input frames
and the previous state meet in a ``tensor_mux``, a filter computes the new
state, a ``tee`` sends it both downstream and back through
``tensor_reposink`` → ``tensor_reposrc``.  The reposrc bootstraps the loop
with a zero frame, so frame 0 sees state 0.

Here the "RNN" is an exponential moving average over the video stream's
mean brightness: state' = 0.9·state + 0.1·frame_mean.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.filter.backends.custom import register_custom_easy  # noqa: E402
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsInfo  # noqa: E402
from nnstreamer_tpu.tensor.types import TensorType  # noqa: E402


def main() -> None:
    f32 = TensorType.FLOAT32
    state_info = TensorsInfo([TensorInfo(dtype=f32, dims=(1,))])
    pair = TensorsInfo([
        TensorInfo(dtype=TensorType.UINT8, dims=(3, 64, 64, 1)),
        TensorInfo(dtype=f32, dims=(1,)),
    ])
    register_custom_easy(
        "ema_state",
        lambda ins: [np.asarray(
            0.9 * np.asarray(ins[1], np.float32)
            + 0.1 * np.asarray(ins[0], np.float32).mean(), np.float32
        ).reshape(1)],
        pair, state_info)

    caps = ("other/tensors,format=static,num_tensors=1,dimensions=1,"
            "types=float32,framerate=0/1")
    p = parse_launch(
        "tensor_mux name=mux sync-mode=nosync ! "
        "tensor_filter framework=custom-easy model=ema_state ! "
        "tee name=t ! queue ! tensor_reposink slot-index=0 "
        "videotestsrc num-buffers=30 pattern=gradient ! "
        "video/x-raw,format=RGB,width=64,height=64,framerate=30/1 ! "
        "tensor_converter ! mux.sink_0 "
        f"tensor_reposrc slot-index=0 caps={caps} ! mux.sink_1 "
        "t. ! queue ! tensor_sink name=out")
    p.get("out").connect(
        "new-data",
        lambda b: print(f"EMA brightness: "
                        f"{float(np.asarray(b.tensors[0]).ravel()[0]):.3f}"))
    p.run(timeout=120)


if __name__ == "__main__":
    main()
