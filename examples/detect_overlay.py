"""Object detection with a drawn overlay (BASELINE config 2).

SSD-MobileNetV2 → bounding_boxes decoder (box-prior decode, NMS, label
sprites) → RGBA overlay written to /tmp/overlay.rgba.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# honor JAX_PLATFORMS even when a sitecustomize pre-selects the TPU
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.models.registry import (get_model,  # noqa: E402
                                            graft_params, restore_params,
                                            save_checkpoint)

REF = "/root/reference/tests/test_models"
MNET_CKPT = "/tmp/nns_tpu_mobilenet_ckpt"
SSD_CKPT = "/tmp/nns_tpu_ssd_graft_ckpt"


def grafted_checkpoint_props() -> str:
    """When the reference artifacts exist, graft the REAL ImageNet
    MobileNetV2 trunk under the SSD head (the heads stay untrained — the
    reference zoo ships no SSD weights either), so decode sees
    real-graph activation scales."""
    tfl = os.path.join(REF, "models", "mobilenet_v2_1.0_224_quant.tflite")
    if not os.path.isfile(tfl):
        return "seed:0"
    if os.path.isdir(SSD_CKPT):
        # cached from an earlier run: make sure it still matches the
        # CURRENT model definition before trusting it
        import shutil

        try:
            ssd = get_model("ssd_mobilenet_v2",
                            {"seed": "0", "dtype": "float32"})
            restore_params(ssd.params, SSD_CKPT)
        except Exception:
            shutil.rmtree(SSD_CKPT, ignore_errors=True)
    if not os.path.isdir(SSD_CKPT):
        if not os.path.isdir(MNET_CKPT):
            sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                            "..", "tools"))
            from tflite_weights import import_weights

            import_weights("mobilenet_v2", tfl, MNET_CKPT)
        mnet = get_model("mobilenet_v2", {"seed": "0", "dtype": "float32"})
        real = restore_params(mnet.params, MNET_CKPT)
        ssd = get_model("ssd_mobilenet_v2",
                        {"seed": "0", "dtype": "float32"})
        ssd.params, n = graft_params(ssd.params, real)
        if n < 100:
            # trunk naming drifted — better a random demo than a stale
            # checkpoint masquerading as real weights
            print(f"graft matched only {n} leaves; using fresh init")
            return "seed:0"
        print(f"grafted {n} real-trunk leaves under the SSD head")
        save_checkpoint(ssd, SSD_CKPT)
    return f"seed:0,checkpoint:{SSD_CKPT},dtype:float32"


def priors_file(n: int) -> str:
    """Synthetic box priors (a real deployment loads the model's
    box_priors.txt, reference tests/test_models/data)."""
    rng = np.random.default_rng(0)
    f = tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False)
    for row in (rng.random(n), rng.random(n),
                np.full(n, 0.2), np.full(n, 0.2)):
        f.write(" ".join(f"{v:.6f}" for v in row) + "\n")
    f.close()
    return f.name


def main() -> None:
    n_anchors = get_model("ssd_mobilenet_v2",
                          {"seed": "0"}).out_info[0].np_shape[0]
    labels = tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False)
    labels.write("\n".join(f"class{i}" for i in range(91)))
    labels.close()
    p = parse_launch(
        "videotestsrc num-buffers=8 pattern=random ! "
        "video/x-raw,format=RGB,width=300,height=300,framerate=30/1 ! "
        "tensor_converter ! "
        "tensor_filter framework=xla model=ssd_mobilenet_v2 "
        f"custom={grafted_checkpoint_props()} ! "
        "tensor_decoder mode=bounding_boxes option1=mobilenet-ssd "
        f"option2={labels.name} option3={priors_file(n_anchors)} "
        "option4=640:480 option5=300:300 option6=0.3 ! "
        "tensor_sink name=out")
    frames = []
    p.get("out").connect("new-data", lambda b: frames.append(b))
    p.run(timeout=600)
    overlay = frames[-1].np(0)
    out = "/tmp/overlay.rgba"
    overlay.tofile(out)
    objs = frames[-1].extra["objects"]
    print(f"{len(frames)} frames; last frame: {len(objs)} detections "
          f"→ {out} ({overlay.shape})")
    for o in objs[:5]:
        print(f"  {o.label or o.class_id}: score={o.score:.2f} "
              f"box=({o.ymin:.2f},{o.xmin:.2f},{o.ymax:.2f},{o.xmax:.2f})")


if __name__ == "__main__":
    main()
